"""Loss layers (analogue of python/paddle/nn/layer/loss.py)."""

from __future__ import annotations

from .. import functional as F
from .layers import Layer

__all__ = ["CrossEntropyLoss", "MSELoss", "L1Loss", "NLLLoss", "BCELoss",
           "BCEWithLogitsLoss", "SmoothL1Loss", "KLDivLoss",
           "MarginRankingLoss", "HingeEmbeddingLoss", "CosineEmbeddingLoss",
           "TripletMarginLoss", "SigmoidFocalLoss", "SoftMarginLoss",
           "MultiLabelSoftMarginLoss", "PoissonNLLLoss", "CTCLoss",
           "RNNTLoss"]


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True,
                 label_smoothing=0.0, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax
        self.label_smoothing = label_smoothing

    def forward(self, input, label):
        return F.cross_entropy(input, label, self.weight, self.ignore_index,
                               self.reduction, self.soft_label, self.axis,
                               self.use_softmax, self.label_smoothing)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index,
                          self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None,
                 name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self.weight, self.reduction, self.pos_weight)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean", log_target=False):
        super().__init__()
        self.reduction = reduction
        self.log_target = log_target

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction, self.log_target)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin, self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin,
                                       self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.args = (margin, p, epsilon, swap, reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, *self.args)


class SigmoidFocalLoss(Layer):
    def __init__(self, alpha=0.25, gamma=2.0, normalizer=None, reduction="sum",
                 name=None):
        super().__init__()
        self.alpha, self.gamma = alpha, gamma
        self.normalizer = normalizer
        self.reduction = reduction

    def forward(self, logit, label):
        return F.sigmoid_focal_loss(logit, label, self.normalizer, self.alpha,
                                    self.gamma, self.reduction)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self.weight,
                                              self.reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self.args = (log_input, full, epsilon, reduction)

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, *self.args)


class RNNTLoss(Layer):
    """RNN-Transducer loss layer (reference
    ``python/paddle/nn/layer/loss.py:1261`` over warp-transducer; see
    ``F.rnnt_loss`` for the lax.scan DP formulation)."""

    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           blank=self.blank,
                           fastemit_lambda=self.fastemit_lambda,
                           reduction=self.reduction)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction, norm_by_times)
