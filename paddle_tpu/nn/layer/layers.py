"""Layer — the module base class.

Analogue of ``paddle.nn.Layer`` (reference:
``python/paddle/nn/layer/layers.py:340``): named parameters/buffers/sublayers,
forward pre/post hooks, state_dict/set_state_dict, train/eval mode, ``to``
dtype conversion, ``apply``.  Parameters are eager Tensors; the jit path lifts
them functionally (see paddle_tpu.jit), so one Layer definition serves both
eager UX and compiled SPMD execution — the TPU-native replacement for the
reference's dygraph/static dual stack.
"""

from __future__ import annotations

import weakref
from collections import OrderedDict
from typing import Callable, Iterator, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ...core.dtypes import convert_dtype, default_float_dtype
from ...core.tensor import Tensor

# global registry used by jit param discovery & distributed init
_ALL_PARAMETERS: "weakref.WeakSet[Parameter]" = weakref.WeakSet()


class Parameter(Tensor):
    """Trainable parameter (analogue of paddle's Parameter/EagerParamBase)."""

    def __init__(self, value, trainable: bool = True, name: Optional[str] = None):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self._is_param = True
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.is_distributed = False
        _ALL_PARAMETERS.add(self)

    @property
    def trainable(self):
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, v):
        self.stop_gradient = not v


class ParamAttr:
    """Analogue of paddle.ParamAttr."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if attr is False:
            return False
        # an Initializer instance
        return ParamAttr(initializer=attr)


class Layer:
    def __init__(self, name_scope=None, dtype=None):
        self.training = True
        self._dtype = convert_dtype(dtype) if dtype else default_float_dtype()
        self._parameters: "OrderedDict[str, Parameter]" = OrderedDict()
        self._buffers: "OrderedDict[str, Tensor]" = OrderedDict()
        self._non_persistable_buffer_names = set()
        self._sub_layers: "OrderedDict[str, Layer]" = OrderedDict()
        self._forward_pre_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self._forward_post_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self._hook_id = 0
        self._name = name_scope or self.__class__.__name__.lower()

    # ---- attribute routing ----
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call super().__init__() first")
            params[name] = value
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            layers[name] = value
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            self.__dict__.pop(name, None)
        elif buffers is not None and name in buffers:
            if value is None or isinstance(value, Tensor):
                buffers[name] = value
            else:
                buffers[name] = Tensor(jnp.asarray(value))
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    # ---- construction helpers ----
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        from ..initializer import Constant, XavierUniform
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = convert_dtype(dtype) or self._dtype
        init = attr.initializer or default_initializer or (
            Constant(0.0) if is_bias else XavierUniform())
        from ..lazy import lazy_init_scope
        with lazy_init_scope():
            value = init(tuple(int(s) for s in shape), dtype)
        p = Parameter(value, trainable=attr.trainable, name=attr.name)
        p.optimize_attr["learning_rate"] = attr.learning_rate
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def add_parameter(self, name, parameter):
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    # ---- iteration ----
    def named_parameters(self, prefix="", include_sublayers=True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (prefix + name if not prefix else prefix + "." + name), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = prefix + "." + lname if prefix else lname
                for item in layer.named_parameters(sub_prefix, True):
                    yield item

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters("", include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (prefix + name if not prefix else prefix + "." + name), b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = prefix + "." + lname if prefix else lname
                for item in layer.named_buffers(sub_prefix, True):
                    yield item

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers("", include_sublayers)]

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = prefix + "." + name if prefix else name
            yield sub_prefix, layer
            for item in layer.named_sublayers(sub_prefix, False):
                yield item

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        return [l for l in self._sub_layers.values() if l is not None]

    def named_children(self):
        return [(n, l) for n, l in self._sub_layers.items() if l is not None]

    def apply(self, fn):
        for layer in self.children():
            layer.apply(fn)
        fn(self)
        return self

    # ---- modes ----
    def train(self):
        self.training = True
        for layer in self.children():
            layer.train()
        return self

    def eval(self):
        self.training = False
        for layer in self.children():
            layer.eval()
        return self

    # ---- hooks ----
    class _HookHandle:
        def __init__(self, store, hid):
            self._store = store
            self._hid = hid

        def remove(self):
            self._store.pop(self._hid, None)

    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return Layer._HookHandle(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return Layer._HookHandle(self._forward_post_hooks, self._hook_id)

    # ---- call ----
    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            rep = repr(layer).split("\n")
            rep = [rep[0]] + ["  " + r for r in rep[1:]]
            lines.append(f"  ({name}): " + "\n".join(rep))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"

    # ---- state dict ----
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        out = OrderedDict() if destination is None else destination
        for name, p in self.named_parameters(structured_name_prefix.rstrip("."),
                                             include_sublayers):
            out[name] = p
        prefix = structured_name_prefix.rstrip(".")
        for name, b in self.named_buffers(prefix, include_sublayers):
            short = name.rsplit(".", 1)[-1]
            # find owning layer to check persistability
            out[name] = b
        # drop non-persistable buffers
        for lname, layer in list(self.named_sublayers("", include_self=True)):
            for bname in layer._non_persistable_buffer_names:
                full = (lname + "." + bname) if lname else bname
                out.pop(full, None)
        return out

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, tensor in own.items():
            if name in state_dict:
                src = state_dict[name]
                arr = src._value if isinstance(src, Tensor) else jnp.asarray(
                    np.asarray(src))
                if tuple(arr.shape) != tuple(tensor._value.shape):
                    raise ValueError(
                        f"shape mismatch for {name}: checkpoint "
                        f"{tuple(arr.shape)} vs model {tuple(tensor._value.shape)}")
                tensor.set_value(arr)
            else:
                missing.append(name)
        for name in state_dict:
            if name not in own:
                unexpected.append(name)
        return missing, unexpected

    load_dict = set_state_dict

    # ---- dtype/device movement ----
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            d = convert_dtype(dtype)
            self._dtype = d
            for t in list(self.parameters()) + list(self.buffers()):
                if jnp.issubdtype(t._value.dtype, jnp.floating):
                    t._value = t._value.astype(d)
                    t._node = None
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def half(self):
        return self.to(dtype="float16")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def full_name(self):
        return self._name

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()
