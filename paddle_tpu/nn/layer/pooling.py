"""Pooling layers (analogue of python/paddle/nn/layer/pooling.py)."""

from __future__ import annotations

from .. import functional as F
from .layers import Layer

__all__ = ["AvgPool1D", "AvgPool2D", "AvgPool3D", "MaxPool1D", "MaxPool2D",
           "MaxPool3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool2D",
           "AdaptiveAvgPool3D", "AdaptiveMaxPool1D", "AdaptiveMaxPool2D",
           "AdaptiveMaxPool3D"]


class _Pool(Layer):
    _fn = None

    def __init__(self, kernel_size, stride=None, padding=0, **kwargs):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.kwargs = {k: v for k, v in kwargs.items() if k != "name"}

    def forward(self, x):
        return getattr(F, self._fn)(x, self.kernel_size, self.stride,
                                    self.padding, **self.kwargs)


class AvgPool1D(_Pool):
    _fn = "avg_pool1d"


class AvgPool2D(_Pool):
    _fn = "avg_pool2d"


class AvgPool3D(_Pool):
    _fn = "avg_pool3d"


class MaxPool1D(_Pool):
    _fn = "max_pool1d"


class MaxPool2D(_Pool):
    _fn = "max_pool2d"


class MaxPool3D(_Pool):
    _fn = "max_pool3d"


class _AdaptivePool(Layer):
    _fn = None

    def __init__(self, output_size, **kwargs):
        super().__init__()
        self.output_size = output_size
        self.kwargs = {k: v for k, v in kwargs.items() if k != "name"}

    def forward(self, x):
        return getattr(F, self._fn)(x, self.output_size, **self.kwargs)


class AdaptiveAvgPool1D(_AdaptivePool):
    _fn = "adaptive_avg_pool1d"


class AdaptiveAvgPool2D(_AdaptivePool):
    _fn = "adaptive_avg_pool2d"


class AdaptiveAvgPool3D(_AdaptivePool):
    _fn = "adaptive_avg_pool3d"


class AdaptiveMaxPool1D(_AdaptivePool):
    _fn = "adaptive_max_pool1d"


class AdaptiveMaxPool2D(_AdaptivePool):
    _fn = "adaptive_max_pool2d"


class AdaptiveMaxPool3D(_AdaptivePool):
    _fn = "adaptive_max_pool3d"
