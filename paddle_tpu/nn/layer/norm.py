"""Norm layers (analogue of python/paddle/nn/layer/norm.py)."""

from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from .. import functional as F
from ..initializer import Constant
from .layers import Layer

__all__ = ["BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
           "SyncBatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm1D",
           "InstanceNorm2D", "InstanceNorm3D", "LocalResponseNorm", "RMSNorm",
           "SpectralNorm"]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = None if weight_attr is False else self.create_parameter(
            (num_features,), attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_features,), attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros((num_features,))))
        self.register_buffer("_variance", Tensor(jnp.ones((num_features,))))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCHW" if data_format == "NCL" else "NHWC",
                         use_global_stats, name)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats, name)


class SyncBatchNorm(_BatchNormBase):
    """On TPU SPMD, batch stats are computed over the global batch by XLA when
    the batch axis is sharded (GSPMD inserts the cross-replica reduction), so
    SyncBatchNorm == BatchNorm under jit (reference:
    python/paddle/nn/layer/norm.py SyncBatchNorm uses nccl allreduce)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, cls):
            new = cls(layer._num_features, layer._momentum, layer._epsilon,
                      data_format=layer._data_format)
            if layer.weight is not None:
                new.weight.set_value(layer.weight)
                new.bias.set_value(layer.bias)
            new._mean.set_value(layer._mean)
            new._variance.set_value(layer._variance)
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self._normalized_shape = tuple(normalized_shape)
        self._epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            self._normalized_shape, attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={list(self._normalized_shape)}"


class RMSNorm(Layer):
    """RMSNorm layer (reference exposes it as incubate fused_rms_norm;
    promoted to a first-class layer here since it is the LLM workhorse)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            (hidden_size,), attr=weight_attr,
            default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            (num_channels,), attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            (num_features,), attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_features,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        from ..initializer import Normal
        self.weight_u = self.create_parameter(
            (h,), default_initializer=Normal(0.0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            (w,), default_initializer=Normal(0.0, 1.0))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ...core.dispatch import dispatch
        import jax

        dim, eps, iters = self._dim, self._epsilon, self._power_iters

        def impl(w, u0, v0):
            wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            u, v = u0, v0
            for _ in range(iters):
                v = wm.T @ u
                v = v / jnp.maximum(jnp.linalg.norm(v), eps)
                u = wm @ v
                u = u / jnp.maximum(jnp.linalg.norm(u), eps)
            sigma = u @ wm @ v
            return w / sigma

        return dispatch("spectral_norm", impl,
                        (weight, self.weight_u, self.weight_v),
                        nondiff_mask=[False, True, True])
