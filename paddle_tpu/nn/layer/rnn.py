"""Recurrent layers (analogue of python/paddle/nn/layer/rnn.py).

The whole sequence recurrence runs as ONE dispatched op whose impl is a
``lax.scan`` — compiler-friendly control flow instead of the reference's
per-timestep C++ loop (``paddle/phi/kernels/gpu/rnn_kernel.cu``), so jit
produces a single fused while-loop on TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import dispatch
from ..initializer import Uniform
from .layers import Layer

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "SimpleRNN", "LSTM",
           "GRU", "BiRNN"]


class RNNCellBase(Layer):
    def _init_params(self, input_size, hidden_size, gates, weight_ih_attr=None,
                     weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None):
        std = 1.0 / np.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            (gates * hidden_size, input_size), attr=weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            (gates * hidden_size, hidden_size), attr=weight_hh_attr,
            default_initializer=init)
        self.bias_ih = None if bias_ih_attr is False else self.create_parameter(
            (gates * hidden_size,), attr=bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = None if bias_hh_attr is False else self.create_parameter(
            (gates * hidden_size,), attr=bias_hh_attr, is_bias=True,
            default_initializer=init)


def _cell_step_fns(mode):
    if mode == "LSTM":
        def step(x, hc, w_ih, w_hh, b):
            h, c = hc
            gates = x @ w_ih.T + h @ w_hh.T + b
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = f * c + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, (h_new, c_new)
        return step
    if mode == "GRU":
        def step(x, hc, w_ih, w_hh, b_split):
            h = hc[0]
            b_ih, b_hh = b_split
            gi = x @ w_ih.T + b_ih
            gh = h @ w_hh.T + b_hh
            ri, zi, ni = jnp.split(gi, 3, axis=-1)
            rh, zh, nh = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ri + rh)
            z = jax.nn.sigmoid(zi + zh)
            n = jnp.tanh(ni + r * nh)
            h_new = (1 - z) * n + z * h
            return h_new, (h_new,)
        return step

    def step(x, hc, w_ih, w_hh, b):
        h_new = jnp.tanh(x @ w_ih.T + hc[0] @ w_hh.T + b)
        return h_new, (h_new,)
    return step


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self._init_params(input_size, hidden_size, 1, weight_ih_attr,
                          weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        from ...tensor.creation import zeros
        if states is None:
            states = zeros([inputs.shape[0], self.hidden_size], inputs.dtype)

        def impl(x, h, w_ih, w_hh, b_ih, b_hh):
            return jnp.tanh(x @ w_ih.T + h @ w_hh.T + b_ih + b_hh)

        h = dispatch("simple_rnn_cell", impl,
                     (inputs, states, self.weight_ih, self.weight_hh,
                      self.bias_ih, self.bias_hh))
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self._init_params(input_size, hidden_size, 4, weight_ih_attr,
                          weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        from ...tensor.creation import zeros
        if states is None:
            z = zeros([inputs.shape[0], self.hidden_size], inputs.dtype)
            states = (z, z)
        h0, c0 = states

        def impl(x, h, c, w_ih, w_hh, b_ih, b_hh):
            step = _cell_step_fns("LSTM")
            h_new, (h2, c2) = step(x, (h, c), w_ih, w_hh, b_ih + b_hh)
            return h2, c2

        h, c = dispatch("lstm_cell", impl,
                        (inputs, h0, c0, self.weight_ih, self.weight_hh,
                         self.bias_ih, self.bias_hh))
        return h, (h, c)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self._init_params(input_size, hidden_size, 3, weight_ih_attr,
                          weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        from ...tensor.creation import zeros
        if states is None:
            states = zeros([inputs.shape[0], self.hidden_size], inputs.dtype)

        def impl(x, h, w_ih, w_hh, b_ih, b_hh):
            step = _cell_step_fns("GRU")
            h_new, _ = step(x, (h,), w_ih, w_hh, (b_ih, b_hh))
            return h_new

        h = dispatch("gru_cell", impl,
                     (inputs, states, self.weight_ih, self.weight_hh,
                      self.bias_ih, self.bias_hh))
        return h, h


class RNN(Layer):
    """Wraps a cell into a scan over time (reference RNN wrapper)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        # generic path: python loop in eager; used for custom cells
        seq_axis = 0 if self.time_major else 1
        steps = inputs.shape[seq_axis]
        outs = []
        state = initial_states
        order = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        from ...tensor.manipulation import stack
        for t in order:
            xt = inputs[:, t] if seq_axis == 1 else inputs[t]
            out, state = self.cell(xt, state)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        return stack(outs, axis=seq_axis), state


class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        num_dir = 2 if self.bidirect else 1
        gates = {"LSTM": 4, "GRU": 3, "RNN_TANH": 1}[mode]
        std = 1.0 / np.sqrt(hidden_size)
        init = Uniform(-std, std)
        self._all_weights = []
        for layer in range(num_layers):
            for direction_i in range(num_dir):
                in_sz = input_size if layer == 0 else hidden_size * num_dir
                suffix = "_reverse" if direction_i else ""
                w_ih = self.create_parameter((gates * hidden_size, in_sz),
                                             default_initializer=init)
                w_hh = self.create_parameter((gates * hidden_size, hidden_size),
                                             default_initializer=init)
                b_ih = self.create_parameter((gates * hidden_size,),
                                             is_bias=True,
                                             default_initializer=init)
                b_hh = self.create_parameter((gates * hidden_size,),
                                             is_bias=True,
                                             default_initializer=init)
                setattr(self, f"weight_ih_l{layer}{suffix}", w_ih)
                setattr(self, f"weight_hh_l{layer}{suffix}", w_hh)
                setattr(self, f"bias_ih_l{layer}{suffix}", b_ih)
                setattr(self, f"bias_hh_l{layer}{suffix}", b_hh)
                self._all_weights.append((w_ih, w_hh, b_ih, b_hh))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor.creation import zeros
        mode = self.mode
        num_dir = 2 if self.bidirect else 1
        b_axis = 1 if self.time_major else 0
        batch = inputs.shape[b_axis]
        if initial_states is None:
            shape = [self.num_layers * num_dir, batch, self.hidden_size]
            if mode == "LSTM":
                initial_states = (zeros(shape, inputs.dtype),
                                  zeros(shape, inputs.dtype))
            else:
                initial_states = zeros(shape, inputs.dtype)

        is_lstm = mode == "LSTM"
        h0 = initial_states[0] if is_lstm else initial_states
        c0 = initial_states[1] if is_lstm else None
        time_major = self.time_major
        num_layers = self.num_layers
        step = _cell_step_fns("LSTM" if is_lstm else
                              ("GRU" if mode == "GRU" else "RNN"))

        flat_weights = [w for tup in self._all_weights for w in tup]

        def impl(x, h_all, *rest):
            if is_lstm:
                c_all = rest[0]
                ws = rest[1:]
            else:
                c_all = None
                ws = rest
            seq = x if time_major else jnp.swapaxes(x, 0, 1)  # T,B,F
            layer_in = seq
            h_outs, c_outs = [], []
            idx = 0
            for layer in range(num_layers):
                dir_outs = []
                for d in range(num_dir):
                    w_ih, w_hh, b_ih, b_hh = ws[4 * idx:4 * idx + 4]
                    idx += 1
                    state_i = layer * num_dir + d
                    h_init = h_all[state_i]
                    carry = (h_init, c_all[state_i]) if is_lstm else (h_init,)

                    xs = jnp.flip(layer_in, 0) if d == 1 else layer_in

                    def scan_step(carry_s, xt, w_ih=w_ih, w_hh=w_hh,
                                  b_ih=b_ih, b_hh=b_hh):
                        if mode == "GRU":
                            out, new = step(xt, carry_s, w_ih, w_hh,
                                            (b_ih, b_hh))
                        else:
                            out, new = step(xt, carry_s, w_ih, w_hh,
                                            b_ih + b_hh)
                        return new, out

                    final, ys = jax.lax.scan(scan_step, carry, xs)
                    if d == 1:
                        ys = jnp.flip(ys, 0)
                    dir_outs.append(ys)
                    h_outs.append(final[0])
                    if is_lstm:
                        c_outs.append(final[1])
                layer_in = jnp.concatenate(dir_outs, axis=-1) if num_dir == 2 \
                    else dir_outs[0]
            out = layer_in if time_major else jnp.swapaxes(layer_in, 0, 1)
            h_stack = jnp.stack(h_outs, axis=0)
            if is_lstm:
                return out, h_stack, jnp.stack(c_outs, axis=0)
            return out, h_stack

        if is_lstm:
            out, h, c = dispatch("lstm", impl,
                                 (inputs, h0, c0, *flat_weights))
            return out, (h, c)
        out, h = dispatch(mode.lower(), impl, (inputs, h0, *flat_weights))
        return out, h


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        super().__init__("RNN_TANH", input_size, hidden_size, num_layers,
                         direction, time_major, dropout)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout)


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.bw = RNN(cell_bw, is_reverse=True, time_major=time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor.manipulation import concat
        states_fw, states_bw = (initial_states if initial_states is not None
                                else (None, None))
        out_fw, st_fw = self.fw(inputs, states_fw)
        out_bw, st_bw = self.bw(inputs, states_bw)
        return concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)
