"""Lazy parameter initialization.

Capability analogue of ``paddle.LazyGuard``
(reference: python/paddle/nn/initializer/lazy_init.py — defer parameter
materialization so huge models can be constructed before sharding).  The
TPU design: parameters created under the guard are placed in **host (CPU)
memory** instead of accelerator HBM; they move to the device (or to their
sharded placement) the first time compute touches them or when an
explicit ``shard_tensor``/``device_put`` assigns their layout.  This is
the deferral that matters on TPU — a 70B model's fp32 init fits in host
RAM while the mesh placement decides where each shard lives.
"""

from __future__ import annotations

import jax

__all__ = ["LazyGuard", "in_lazy_mode"]

_LAZY = False


def in_lazy_mode() -> bool:
    return _LAZY


class LazyGuard:
    """with LazyGuard(): model = BigModel()  -> params live on host."""

    def __enter__(self):
        global _LAZY
        self._prev = _LAZY
        _LAZY = True
        return self

    def __exit__(self, *exc):
        global _LAZY
        _LAZY = self._prev
        return False


def lazy_init_scope():
    """Context under which parameter initializers run: in lazy mode the
    whole init computation executes with the CPU as JAX's default device,
    so the values are *born* in host RAM (never touching HBM — the point
    of lazy init for models larger than a chip); otherwise a no-op."""
    import contextlib
    if not _LAZY:
        return contextlib.nullcontext()
    cpus = jax.devices("cpu")
    if not cpus:
        return contextlib.nullcontext()
    return jax.default_device(cpus[0])
