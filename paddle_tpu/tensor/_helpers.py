"""Shared helpers for tensor op definitions."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import dispatch
from ..core.tensor import Tensor


def is_scalar(x):
    return isinstance(x, (int, float, bool, complex))


def binop(name, fn, x, y):
    """Binary op dispatch keeping python scalars weakly-typed (closed over)."""
    if is_scalar(y) and not is_scalar(x):
        return dispatch(name, lambda a: fn(a, y), (x,))
    if is_scalar(x) and not is_scalar(y):
        return dispatch(name, lambda b: fn(x, b), (y,))
    return dispatch(name, fn, (x, y))


def unop(name, fn, x):
    return dispatch(name, fn, (x,))


def normalize_axis(axis):
    if isinstance(axis, Tensor):
        return tuple(int(v) for v in axis.numpy().reshape(-1))
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    if axis is None:
        return None
    return int(axis)


def normalize_shape(shape):
    """Shapes may be int lists or Tensors (static values only under XLA)."""
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in shape.numpy().reshape(-1))
    if isinstance(shape, (list, tuple)):
        return tuple(
            int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape
        )
    return (int(shape),)


def asarray(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)
