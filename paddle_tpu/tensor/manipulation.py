"""Shape/layout manipulation ops (analogue of python/paddle/tensor/manipulation.py).

Note on XLA semantics: ops whose output shape depends on data (masked_select,
nonzero-driven gathers) are eager-only — under jit they raise with a clear
message, mirroring how the reference routes them through dynamic-shape
infershape that XLA cannot express (SURVEY §7 "Hard parts": bucketing policy).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import dispatch
from ..core.tensor import Tensor
from ._helpers import asarray, normalize_shape, normalize_axis

__all__ = [
    "reshape", "reshape_", "transpose", "concat", "stack", "split", "chunk",
    "squeeze", "squeeze_", "unsqueeze", "unsqueeze_", "flatten", "tile",
    "expand", "expand_as", "broadcast_to", "broadcast_tensors", "flip",
    "roll", "gather", "gather_nd", "scatter", "scatter_", "scatter_nd",
    "scatter_nd_add", "index_select", "index_sample", "index_add",
    "index_put", "masked_select", "masked_fill", "slice", "strided_slice",
    "crop", "pad", "unbind", "unstack", "repeat_interleave",
    "take_along_axis", "put_along_axis", "moveaxis", "rot90",
    "as_complex", "as_real", "view", "view_as", "tensor_split", "hsplit",
    "vsplit", "dsplit", "hstack", "vstack", "dstack", "row_stack",
    "column_stack", "atleast_1d", "atleast_2d", "atleast_3d", "unflatten",
    "unique", "unique_consecutive", "bincount", "one_hot", "numel", "rank",
    "shard_index", "flatten_", "cast", "cast_", "tolist", "chunk",
]


def cast(x, dtype):
    return x.astype(dtype) if isinstance(x, Tensor) else Tensor(asarray(x)).astype(dtype)


def cast_(x, dtype):
    x._in_place_update(x.astype(dtype))
    return x


def tolist(x):
    return x.tolist()


def reshape(x, shape, name=None):
    sh = normalize_shape(shape)
    return dispatch("reshape", lambda a: jnp.reshape(a, sh), (x,))


def reshape_(x, shape, name=None):
    x._in_place_update(reshape(x, shape))
    return x


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return dispatch("view_dtype",
                    lambda a: a.view(shape_or_dtype)
                    if hasattr(a, "view") else a, (x,))


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def transpose(x, perm, name=None):
    perm = [int(p) for p in perm]
    return dispatch("transpose", lambda a: jnp.transpose(a, perm), (x,))


def moveaxis(x, source, destination, name=None):
    return dispatch("moveaxis",
                    lambda a: jnp.moveaxis(a, source, destination), (x,))


def concat(x, axis=0, name=None):
    tensors = list(x)
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return dispatch("concat", lambda *arrays: jnp.concatenate(arrays, axis=ax),
                    tuple(tensors))


def stack(x, axis=0, name=None):
    tensors = list(x)
    return dispatch("stack", lambda *arrays: jnp.stack(arrays, axis=axis),
                    tuple(tensors))


def hstack(x, name=None):
    return dispatch("hstack", lambda *arrays: jnp.hstack(arrays), tuple(x))


def vstack(x, name=None):
    return dispatch("vstack", lambda *arrays: jnp.vstack(arrays), tuple(x))


def dstack(x, name=None):
    return dispatch("dstack", lambda *arrays: jnp.dstack(arrays), tuple(x))


row_stack = vstack


def column_stack(x, name=None):
    return dispatch("column_stack",
                    lambda *arrays: jnp.column_stack(arrays), tuple(x))


def split(x, num_or_sections, axis=0, name=None):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)

    def impl(a):
        if isinstance(num_or_sections, int):
            return tuple(jnp.split(a, num_or_sections, axis=ax))
        sections = [int(s) for s in num_or_sections]
        total = a.shape[ax]
        # paddle allows one -1 section
        neg = [i for i, s in enumerate(sections) if s == -1]
        if neg:
            known = sum(s for s in sections if s != -1)
            sections[neg[0]] = total - known
        splits = np.cumsum(sections)[:-1].tolist()
        return tuple(jnp.split(a, splits, axis=ax))

    out = dispatch("split", impl, (x,))
    return list(out)


def tensor_split(x, num_or_indices, axis=0, name=None):
    return list(dispatch(
        "tensor_split",
        lambda a: tuple(jnp.array_split(a, num_or_indices, axis=axis)), (x,)))


def hsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=1 if x.ndim > 1 else 0)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def squeeze(x, axis=None, name=None):
    ax = normalize_axis(axis)

    def impl(a):
        if ax is None:
            return jnp.squeeze(a)
        axes = (ax,) if isinstance(ax, int) else ax
        axes = tuple(a_ % a.ndim for a_ in axes if a.shape[a_ % a.ndim] == 1)
        return jnp.squeeze(a, axis=axes) if axes else a

    return dispatch("squeeze", impl, (x,))


def squeeze_(x, axis=None, name=None):
    x._in_place_update(squeeze(x, axis))
    return x


def unsqueeze(x, axis, name=None):
    ax = normalize_axis(axis)
    axes = (ax,) if isinstance(ax, int) else ax

    def impl(a):
        out = a
        for a_ in sorted(axes):
            out = jnp.expand_dims(out, a_)
        return out

    return dispatch("unsqueeze", impl, (x,))


def unsqueeze_(x, axis, name=None):
    x._in_place_update(unsqueeze(x, axis))
    return x


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def impl(a):
        nd = a.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = a.shape[:s] + (-1,) + a.shape[e + 1:]
        return jnp.reshape(a, new_shape)

    return dispatch("flatten", impl, (x,))


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    x._in_place_update(flatten(x, start_axis, stop_axis))
    return x


def unflatten(x, axis, shape, name=None):
    sh = normalize_shape(shape)

    def impl(a):
        ax = axis % a.ndim
        return jnp.reshape(a, a.shape[:ax] + tuple(sh) + a.shape[ax + 1:])

    return dispatch("unflatten", impl, (x,))


def tile(x, repeat_times, name=None):
    reps = normalize_shape(repeat_times)
    return dispatch("tile", lambda a: jnp.tile(a, reps), (x,))


def expand(x, shape, name=None):
    sh = normalize_shape(shape)

    def impl(a):
        target = list(sh)
        # -1 means keep original dim
        offset = len(target) - a.ndim
        for i in range(len(target)):
            if target[i] == -1:
                target[i] = a.shape[i - offset]
        return jnp.broadcast_to(a, tuple(target))

    return dispatch("expand", impl, (x,))


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    return list(dispatch("broadcast_tensors",
                         lambda *arrays: tuple(jnp.broadcast_arrays(*arrays)),
                         tuple(inputs)))


def flip(x, axis, name=None):
    ax = normalize_axis(axis)
    return dispatch("flip", lambda a: jnp.flip(a, axis=ax), (x,))


def rot90(x, k=1, axes=(0, 1), name=None):
    return dispatch("rot90", lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), (x,))


def roll(x, shifts, axis=None, name=None):
    ax = normalize_axis(axis)
    sh = shifts if isinstance(shifts, int) else tuple(int(s) for s in np.atleast_1d(np.asarray(shifts)))

    def impl(a):
        if ax is None:
            return jnp.roll(a.reshape(-1), sh).reshape(a.shape)
        return jnp.roll(a, sh, axis=ax)

    return dispatch("roll", impl, (x,))


def gather(x, index, axis=0, name=None):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)

    def impl(a, idx):
        return jnp.take(a, idx.reshape(-1).astype(jnp.int32), axis=ax)

    return dispatch("gather", impl, (x, index), nondiff_mask=[False, True])


def gather_nd(x, index, name=None):
    def impl(a, idx):
        idx = idx.astype(jnp.int32)
        k = idx.shape[-1]
        flat_idx = tuple(idx[..., i] for i in range(k))
        return a[flat_idx]

    return dispatch("gather_nd", impl, (x, index), nondiff_mask=[False, True])


def scatter(x, index, updates, overwrite=True, name=None):
    def impl(a, idx, upd):
        idx = idx.reshape(-1).astype(jnp.int32)
        if overwrite:
            return a.at[idx].set(upd)
        # paddle semantics: zero the rows then accumulate
        zeroed = a.at[idx].set(jnp.zeros_like(upd))
        return zeroed.at[idx].add(upd)

    return dispatch("scatter", impl, (x, index, updates),
                    nondiff_mask=[False, True, False])


def scatter_(x, index, updates, overwrite=True, name=None):
    x._in_place_update(scatter(x, index, updates, overwrite))
    return x


def scatter_nd_add(x, index, updates, name=None):
    def impl(a, idx, upd):
        idx = idx.astype(jnp.int32)
        k = idx.shape[-1]
        return a.at[tuple(idx[..., i] for i in range(k))].add(upd)

    return dispatch("scatter_nd_add", impl, (x, index, updates),
                    nondiff_mask=[False, True, False])


def scatter_nd(index, updates, shape, name=None):
    from .creation import zeros
    base = zeros(shape, dtype=updates.dtype if isinstance(updates, Tensor) else None)
    return scatter_nd_add(base, index, updates)


def index_select(x, index, axis=0, name=None):
    def impl(a, idx):
        return jnp.take(a, idx.reshape(-1).astype(jnp.int32), axis=axis)

    return dispatch("index_select", impl, (x, index), nondiff_mask=[False, True])


def index_sample(x, index):
    def impl(a, idx):
        rows = jnp.arange(a.shape[0])[:, None]
        return a[rows, idx.astype(jnp.int32)]

    return dispatch("index_sample", impl, (x, index), nondiff_mask=[False, True])


def index_add(x, index, axis, value, name=None):
    def impl(a, idx, v):
        idx = idx.reshape(-1).astype(jnp.int32)
        moved = jnp.moveaxis(a, axis, 0)
        vmoved = jnp.moveaxis(v, axis, 0)
        out = moved.at[idx].add(vmoved)
        return jnp.moveaxis(out, 0, axis)

    return dispatch("index_add", impl, (x, index, value),
                    nondiff_mask=[False, True, False])


def index_put(x, indices, value, accumulate=False, name=None):
    def impl(a, v, *idx):
        idx = tuple(i.astype(jnp.int32) if jnp.issubdtype(i.dtype, jnp.integer)
                    else i for i in idx)
        if accumulate:
            return a.at[idx].add(v)
        return a.at[idx].set(v)

    return dispatch("index_put", impl, (x, value, *indices),
                    nondiff_mask=[False, False] + [True] * len(indices))


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    def impl(a, idx):
        return jnp.take_along_axis(a, idx.astype(jnp.int32), axis=axis)

    return dispatch("take_along_axis", impl, (arr, indices),
                    nondiff_mask=[False, True])


def put_along_axis(arr, indices, values, axis, reduce="assign",
                   include_self=True, broadcast=True, name=None):
    def impl(a, idx, v):
        idx = idx.astype(jnp.int32)
        v = jnp.broadcast_to(v, idx.shape) if jnp.ndim(v) else jnp.full(idx.shape, v, a.dtype)
        moved_a = jnp.moveaxis(a, axis, 0)
        moved_i = jnp.moveaxis(idx, axis, 0)
        moved_v = jnp.moveaxis(v, axis, 0)
        grid = jnp.indices(moved_i.shape)
        full_idx = (moved_i,) + tuple(grid[1:])
        if reduce == "assign":
            out = moved_a.at[full_idx].set(moved_v)
        elif reduce == "add":
            out = moved_a.at[full_idx].add(moved_v)
        elif reduce == "multiply" or reduce == "mul":
            out = moved_a.at[full_idx].multiply(moved_v)
        elif reduce == "amax":
            out = moved_a.at[full_idx].max(moved_v)
        elif reduce == "amin":
            out = moved_a.at[full_idx].min(moved_v)
        else:
            raise ValueError(f"unsupported reduce {reduce!r}")
        return jnp.moveaxis(out, 0, axis)

    return dispatch("put_along_axis", impl, (arr, indices, values),
                    nondiff_mask=[False, True, False])


def masked_select(x, mask, name=None):
    # dynamic output shape: eager only (see module docstring)
    a, m = asarray(x), asarray(mask)
    if isinstance(a, jax.core.Tracer) or isinstance(m, jax.core.Tracer):
        raise NotImplementedError(
            "masked_select has data-dependent output shape and cannot run "
            "under jit; compute it eagerly or restructure with paddle.where")
    return Tensor(a[np.asarray(m)])


def masked_fill(x, mask, value, name=None):
    v = value.item() if isinstance(value, Tensor) and value.size == 1 else value

    def impl(a, m):
        return jnp.where(m, jnp.asarray(v, a.dtype), a)

    return dispatch("masked_fill", impl, (x, mask), nondiff_mask=[False, True])


def slice(input, axes, starts, ends, name=None):
    def impl(a):
        idx = [np.s_[:]] * a.ndim
        for ax, s, e in zip(axes, starts, ends):
            s = int(s.item()) if isinstance(s, Tensor) else int(s)
            e = int(e.item()) if isinstance(e, Tensor) else int(e)
            idx[ax] = np.s_[s:e]
        return a[tuple(idx)]

    return dispatch("slice", impl, (input,))


def strided_slice(x, axes, starts, ends, strides, name=None):
    def impl(a):
        idx = [np.s_[:]] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = np.s_[int(s):int(e):int(st)]
        return a[tuple(idx)]

    return dispatch("strided_slice", impl, (x,))


def crop(x, shape=None, offsets=None, name=None):
    sh = normalize_shape(shape)
    offs = [0] * len(sh) if offsets is None else [int(o) for o in offsets]

    def impl(a):
        idx = tuple(np.s_[o:o + (s if s != -1 else a.shape[i] - o)]
                    for i, (o, s) in enumerate(zip(offs, sh)))
        return a[idx]

    return dispatch("crop", impl, (x,))


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    pad = [int(p) for p in (pad.tolist() if isinstance(pad, Tensor) else pad)]

    def impl(a):
        nd = a.ndim
        if len(pad) == 2 * nd:
            width = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            # paddle NCHW/NCDHW convention: pad applies to trailing spatial dims,
            # given in reverse (last dim first)
            n_spatial = len(pad) // 2
            width = [(0, 0)] * (nd - n_spatial)
            spatial = [(pad[2 * i], pad[2 * i + 1]) for i in range(n_spatial)]
            if data_format in ("NHWC", "NDHWC", "NLC"):
                width = [(0, 0)] + spatial[::-1] + [(0, 0)]
            else:
                width += spatial[::-1]
        jmode = {"constant": "constant", "reflect": "reflect",
                 "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(a, width, mode=jmode, constant_values=value)
        return jnp.pad(a, width, mode=jmode)

    return dispatch("pad", impl, (x,))


def unbind(input, axis=0, name=None):
    n = input.shape[axis] if isinstance(input, Tensor) else asarray(input).shape[axis]

    def impl(a):
        moved = jnp.moveaxis(a, axis, 0)
        return tuple(moved[i] for i in range(n))

    return list(dispatch("unbind", impl, (input,)))


unstack = unbind


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        reps = np.asarray(repeats.numpy())
        def impl(a):
            return jnp.repeat(a if axis is not None else a.reshape(-1),
                              jnp.asarray(reps), axis=0 if axis is None else axis,
                              total_repeat_length=int(reps.sum()))
        return dispatch("repeat_interleave", impl, (x,))

    def impl(a):
        return jnp.repeat(a if axis is not None else a.reshape(-1), repeats,
                          axis=0 if axis is None else axis)

    return dispatch("repeat_interleave", impl, (x,))


def as_complex(x, name=None):
    return dispatch("as_complex", lambda a: a[..., 0] + 1j * a[..., 1], (x,))


def as_real(x, name=None):
    return dispatch("as_real",
                    lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1),
                    (x,))


def atleast_1d(*inputs, name=None):
    outs = [dispatch("atleast_1d", jnp.atleast_1d, (t,)) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [dispatch("atleast_2d", jnp.atleast_2d, (t,)) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [dispatch("atleast_3d", jnp.atleast_3d, (t,)) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    # dynamic output shape: eager only
    a = asarray(x)
    if isinstance(a, jax.core.Tracer):
        raise NotImplementedError("unique cannot run under jit (dynamic shape)")
    res = np.unique(np.asarray(a), return_index=return_index,
                    return_inverse=return_inverse, return_counts=return_counts,
                    axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    out = [Tensor(jnp.asarray(r)) for r in res]
    return tuple(out)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    a = np.asarray(asarray(x))
    if axis is None:
        a = a.reshape(-1)
        keep = np.ones(len(a), dtype=bool)
        keep[1:] = a[1:] != a[:-1]
        vals = a[keep]
        outs = [Tensor(jnp.asarray(vals))]
        if return_inverse:
            outs.append(Tensor(jnp.asarray(np.cumsum(keep) - 1)))
        if return_counts:
            idx = np.flatnonzero(keep)
            counts = np.diff(np.append(idx, len(a)))
            outs.append(Tensor(jnp.asarray(counts)))
        return outs[0] if len(outs) == 1 else tuple(outs)
    raise NotImplementedError("unique_consecutive with axis is not supported yet")


def bincount(x, weights=None, minlength=0, name=None):
    a = np.asarray(asarray(x))
    w = np.asarray(asarray(weights)) if weights is not None else None
    return Tensor(jnp.asarray(np.bincount(a, weights=w, minlength=minlength)))


def one_hot(x, num_classes, name=None):
    def impl(idx):
        return jax.nn.one_hot(idx.astype(jnp.int32), num_classes, dtype=jnp.float32)

    return dispatch("one_hot", impl, (x,), nondiff_mask=[True])


def numel(x, name=None):
    return Tensor(jnp.asarray(x.size if isinstance(x, Tensor) else asarray(x).size,
                              dtype=jnp.int32))


def rank(input):
    return Tensor(jnp.asarray(input.ndim if isinstance(input, Tensor)
                              else asarray(input).ndim, dtype=jnp.int32))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    shard_size = (index_num + nshards - 1) // nshards

    def impl(idx):
        shard = idx // shard_size
        local = idx % shard_size
        return jnp.where(shard == shard_id, local, ignore_value)

    return dispatch("shard_index", impl, (input,), nondiff_mask=[True])
