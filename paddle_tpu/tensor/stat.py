"""Statistics ops (analogue of python/paddle/tensor/stat.py)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import dispatch
from ._helpers import normalize_axis

__all__ = ["mean", "std", "var", "median", "nanmedian", "quantile",
           "nanquantile", "histogram", "histogramdd", "numel"]

from .math import mean
from .manipulation import numel


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = normalize_axis(axis)
    return dispatch(
        "std",
        lambda a: jnp.std(a, axis=ax, ddof=1 if unbiased else 0, keepdims=keepdim),
        (x,))


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = normalize_axis(axis)
    return dispatch(
        "var",
        lambda a: jnp.var(a, axis=ax, ddof=1 if unbiased else 0, keepdims=keepdim),
        (x,))


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    ax = normalize_axis(axis)

    def impl(a):
        if mode == "avg":
            return jnp.median(a, axis=ax, keepdims=keepdim)
        # 'min' mode: lower of the two middle values
        arr = a.reshape(-1) if ax is None else a
        axis_ = 0 if ax is None else ax
        n = arr.shape[axis_]
        s = jnp.sort(arr, axis=axis_)
        out = jnp.take(s, (n - 1) // 2, axis=axis_)
        if keepdim and ax is not None:
            out = jnp.expand_dims(out, axis_)
        return out

    return dispatch("median", impl, (x,))


def nanmedian(x, axis=None, keepdim=False, mode="avg", name=None):
    ax = normalize_axis(axis)
    return dispatch("nanmedian",
                    lambda a: jnp.nanmedian(a, axis=ax, keepdims=keepdim), (x,))


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    ax = normalize_axis(axis)

    def impl(a):
        qq = jnp.asarray(q)
        return jnp.quantile(a, qq, axis=ax, keepdims=keepdim,
                            method=interpolation)

    return dispatch("quantile", impl, (x,))


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    ax = normalize_axis(axis)
    return dispatch(
        "nanquantile",
        lambda a: jnp.nanquantile(a, jnp.asarray(q), axis=ax, keepdims=keepdim,
                                  method=interpolation),
        (x,))


def histogram(input, bins=100, min=0, max=0, weight=None, density=False, name=None):
    def impl(a, *rest):
        lo, hi = (min, max) if (min != 0 or max != 0) else (a.min(), a.max())
        w = rest[0].reshape(-1) if rest else None
        hist, _ = jnp.histogram(a.reshape(-1), bins=bins, range=(lo, hi),
                                weights=w, density=density)
        return hist if density or w is not None else hist.astype(jnp.int32)

    args = (input, weight) if weight is not None else (input,)
    return dispatch("histogram", impl, args,
                    nondiff_mask=[True] * len(args))


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    def impl(a, *rest):
        w = rest[0] if rest else None
        hist, edges = jnp.histogramdd(a, bins=bins, range=ranges,
                                      weights=w, density=density)
        return (hist,) + tuple(edges)

    args = (x, weights) if weights is not None else (x,)
    out = dispatch("histogramdd", impl, args, nondiff_mask=[True] * len(args))
    return out[0], list(out[1:])
