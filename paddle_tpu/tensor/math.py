"""Math ops (analogue of python/paddle/tensor/math.py).

Every op: eager path through core.dispatch (tape-recorded, AMP-aware),
pure-jax impl underneath so the same function is jit/vjp/shard_map safe.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import dispatch
from ..core.tensor import Tensor
from ._helpers import binop, unop, is_scalar, normalize_axis

__all__ = [
    "add", "subtract", "multiply", "divide", "floor_divide", "mod", "remainder",
    "pow", "matmul", "maximum", "minimum", "fmax", "fmin", "exp", "expm1",
    "log", "log2", "log10", "log1p", "sqrt", "rsqrt", "square", "abs", "sign",
    "sin", "cos", "tan", "asin", "acos", "atan", "atan2", "sinh", "cosh",
    "asinh", "acosh", "atanh", "tanh", "floor", "ceil", "round", "trunc",
    "frac", "reciprocal", "clip", "sum", "nansum", "mean", "nanmean", "max",
    "min", "amax", "amin", "prod", "logsumexp", "cumsum", "cumprod", "cummax",
    "cummin", "isfinite", "isnan", "isinf", "erf", "erfinv", "lerp", "addmm",
    "inner", "outer", "scale", "stanh", "neg", "increment", "kron", "diff",
    "trace", "deg2rad", "rad2deg", "gcd", "lcm", "heaviside", "rsqrt",
    "multiplex", "logit", "digamma", "lgamma", "nan_to_num", "angle",
    "conj", "real", "imag", "sgn", "count_nonzero", "add_n", "hypot",
    "log_normal", "ldexp", "logaddexp", "floor_mod", "inverse",
]


def add(x, y, name=None):
    return binop("add", jnp.add, x, y)


def subtract(x, y, name=None):
    return binop("subtract", jnp.subtract, x, y)


def multiply(x, y, name=None):
    return binop("multiply", jnp.multiply, x, y)


def divide(x, y, name=None):
    return binop("divide", jnp.divide, x, y)


def floor_divide(x, y, name=None):
    return binop("floor_divide", jnp.floor_divide, x, y)


def mod(x, y, name=None):
    return binop("mod", jnp.mod, x, y)


remainder = mod
floor_mod = mod


def pow(x, y, name=None):
    return binop("pow", jnp.power, x, y)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def impl(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)

    return dispatch("matmul", impl, (x, y))


def maximum(x, y, name=None):
    return binop("maximum", jnp.maximum, x, y)


def minimum(x, y, name=None):
    return binop("minimum", jnp.minimum, x, y)


def fmax(x, y, name=None):
    return binop("fmax", jnp.fmax, x, y)


def fmin(x, y, name=None):
    return binop("fmin", jnp.fmin, x, y)


def hypot(x, y, name=None):
    return binop("hypot", jnp.hypot, x, y)


def logaddexp(x, y, name=None):
    return binop("logaddexp", jnp.logaddexp, x, y)


def ldexp(x, y, name=None):
    return binop("ldexp", lambda a, b: a * jnp.power(2.0, b).astype(a.dtype), x, y)


# ---- unary ----
def exp(x, name=None):
    return unop("exp", jnp.exp, x)


def expm1(x, name=None):
    return unop("expm1", jnp.expm1, x)


def log(x, name=None):
    return unop("log", jnp.log, x)


def log2(x, name=None):
    return unop("log2", jnp.log2, x)


def log10(x, name=None):
    return unop("log10", jnp.log10, x)


def log1p(x, name=None):
    return unop("log1p", jnp.log1p, x)


def sqrt(x, name=None):
    return unop("sqrt", jnp.sqrt, x)


def rsqrt(x, name=None):
    return unop("rsqrt", jax.lax.rsqrt, x)


def square(x, name=None):
    return unop("square", jnp.square, x)


def abs(x, name=None):
    return unop("abs", jnp.abs, x)


def sign(x, name=None):
    return unop("sign", jnp.sign, x)


def sgn(x, name=None):
    def impl(a):
        if jnp.issubdtype(a.dtype, jnp.complexfloating):
            mag = jnp.abs(a)
            return jnp.where(mag == 0, 0, a / jnp.where(mag == 0, 1, mag))
        return jnp.sign(a)

    return dispatch("sgn", impl, (x,))


def sin(x, name=None):
    return unop("sin", jnp.sin, x)


def cos(x, name=None):
    return unop("cos", jnp.cos, x)


def tan(x, name=None):
    return unop("tan", jnp.tan, x)


def asin(x, name=None):
    return unop("asin", jnp.arcsin, x)


def acos(x, name=None):
    return unop("acos", jnp.arccos, x)


def atan(x, name=None):
    return unop("atan", jnp.arctan, x)


def atan2(x, y, name=None):
    return binop("atan2", jnp.arctan2, x, y)


def sinh(x, name=None):
    return unop("sinh", jnp.sinh, x)


def cosh(x, name=None):
    return unop("cosh", jnp.cosh, x)


def asinh(x, name=None):
    return unop("asinh", jnp.arcsinh, x)


def acosh(x, name=None):
    return unop("acosh", jnp.arccosh, x)


def atanh(x, name=None):
    return unop("atanh", jnp.arctanh, x)


def tanh(x, name=None):
    return unop("tanh", jnp.tanh, x)


def floor(x, name=None):
    return unop("floor", jnp.floor, x)


def ceil(x, name=None):
    return unop("ceil", jnp.ceil, x)


def round(x, name=None):
    return unop("round", jnp.round, x)


def trunc(x, name=None):
    return unop("trunc", jnp.trunc, x)


def frac(x, name=None):
    return unop("frac", lambda a: a - jnp.trunc(a), x)


def reciprocal(x, name=None):
    return unop("reciprocal", jnp.reciprocal, x)


def neg(x, name=None):
    return unop("neg", jnp.negative, x)


def erf(x, name=None):
    return unop("erf", jax.scipy.special.erf, x)


def erfinv(x, name=None):
    return unop("erfinv", jax.scipy.special.erfinv, x)


def digamma(x, name=None):
    return unop("digamma", jax.scipy.special.digamma, x)


def lgamma(x, name=None):
    return unop("lgamma", jax.scipy.special.gammaln, x)


def logit(x, eps=None, name=None):
    def impl(a):
        z = a if eps is None else jnp.clip(a, eps, 1.0 - eps)
        out = jnp.log(z / (1.0 - z))
        if eps is None:
            out = jnp.where((a < 0) | (a > 1), jnp.nan, out)
        return out

    return dispatch("logit", impl, (x,))


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return dispatch("stanh", lambda a: scale_b * jnp.tanh(scale_a * a), (x,))


def deg2rad(x, name=None):
    return unop("deg2rad", jnp.deg2rad, x)


def rad2deg(x, name=None):
    return unop("rad2deg", jnp.rad2deg, x)


def angle(x, name=None):
    return unop("angle", jnp.angle, x)


def conj(x, name=None):
    return unop("conj", jnp.conj, x)


def real(x, name=None):
    return unop("real", jnp.real, x)


def imag(x, name=None):
    return unop("imag", jnp.imag, x)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return dispatch(
        "nan_to_num",
        lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf),
        (x,))


def gcd(x, y, name=None):
    return binop("gcd", jnp.gcd, x, y)


def lcm(x, y, name=None):
    return binop("lcm", jnp.lcm, x, y)


def heaviside(x, y, name=None):
    return binop("heaviside", jnp.heaviside, x, y)


def clip(x, min=None, max=None, name=None):
    lo = min._value if isinstance(min, Tensor) else min
    hi = max._value if isinstance(max, Tensor) else max
    return dispatch("clip", lambda a: jnp.clip(a, lo, hi), (x,))


def lerp(x, y, weight, name=None):
    if is_scalar(weight):
        return dispatch("lerp", lambda a, b: a + weight * (b - a), (x, y))
    return dispatch("lerp", lambda a, b, w: a + w * (b - a), (x, y, weight))


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s = float(scale) if is_scalar(scale) else scale

    def impl(a, *rest):
        sv = rest[0] if rest else s
        out = a * sv + bias if bias_after_scale else (a + bias) * sv
        return out.astype(a.dtype)

    if is_scalar(scale):
        return dispatch("scale", impl, (x,))
    return dispatch("scale", impl, (x, scale))


def increment(x, value=1.0, name=None):
    out = dispatch("increment", lambda a: a + value, (x,))
    if isinstance(x, Tensor):
        x._in_place_update(out)
        return x
    return out


# ---- reductions ----
def _reduce(name, fn, x, axis, keepdim, dtype=None):
    ax = normalize_axis(axis)

    def impl(a):
        out = fn(a, axis=ax, keepdims=keepdim)
        if dtype is not None:
            out = out.astype(dtype)
        return out

    return dispatch(name, impl, (x,))


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    from ..core.dtypes import convert_dtype
    d = convert_dtype(dtype)
    ax = normalize_axis(axis)

    def impl(a):
        acc = a
        if jnp.issubdtype(a.dtype, jnp.bool_):
            acc = a.astype(jnp.int32)
        out = jnp.sum(acc, axis=ax, keepdims=keepdim)
        return out.astype(d) if d is not None else out

    return dispatch("sum", impl, (x,))


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return _reduce("nansum", jnp.nansum, x, axis, keepdim, dtype)


def mean(x, axis=None, keepdim=False, name=None):
    return _reduce("mean", jnp.mean, x, axis, keepdim)


def nanmean(x, axis=None, keepdim=False, name=None):
    return _reduce("nanmean", jnp.nanmean, x, axis, keepdim)


def max(x, axis=None, keepdim=False, name=None):
    return _reduce("max", jnp.max, x, axis, keepdim)


def min(x, axis=None, keepdim=False, name=None):
    return _reduce("min", jnp.min, x, axis, keepdim)


amax = max
amin = min


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    return _reduce("prod", jnp.prod, x, axis, keepdim, dtype)


def logsumexp(x, axis=None, keepdim=False, name=None):
    ax = normalize_axis(axis)
    return dispatch(
        "logsumexp",
        lambda a: jax.scipy.special.logsumexp(a, axis=ax, keepdims=keepdim),
        (x,))


def count_nonzero(x, axis=None, keepdim=False, name=None):
    ax = normalize_axis(axis)
    return dispatch(
        "count_nonzero",
        lambda a: jnp.count_nonzero(a, axis=ax, keepdims=keepdim).astype(jnp.int32),
        (x,))


def cumsum(x, axis=None, dtype=None, name=None):
    from ..core.dtypes import convert_dtype
    d = convert_dtype(dtype)

    def impl(a):
        arr = a.reshape(-1) if axis is None else a
        out = jnp.cumsum(arr, axis=0 if axis is None else axis)
        return out.astype(d) if d is not None else out

    return dispatch("cumsum", impl, (x,))


def cumprod(x, dim=None, dtype=None, name=None):
    from ..core.dtypes import convert_dtype
    d = convert_dtype(dtype)

    def impl(a):
        out = jnp.cumprod(a, axis=dim)
        return out.astype(d) if d is not None else out

    return dispatch("cumprod", impl, (x,))


def cummax(x, axis=None, dtype="int64", name=None):
    def impl(a):
        arr = a.reshape(-1) if axis is None else a
        ax = 0 if axis is None else axis
        vals = jax.lax.associative_scan(jnp.maximum, arr, axis=ax)
        n = arr.shape[ax]
        idx = jnp.arange(n).reshape([-1 if i == (ax % arr.ndim) else 1
                                     for i in range(arr.ndim)])
        idx = jnp.broadcast_to(idx, arr.shape)
        is_new = arr == vals
        inds = jax.lax.associative_scan(
            jnp.maximum, jnp.where(is_new, idx, -1), axis=ax)
        return vals, inds.astype(jnp.int32)

    return dispatch("cummax", impl, (x,), n_diff_outputs=1)


def cummin(x, axis=None, dtype="int64", name=None):
    def impl(a):
        arr = a.reshape(-1) if axis is None else a
        ax = 0 if axis is None else axis
        vals = jax.lax.associative_scan(jnp.minimum, arr, axis=ax)
        idx = jnp.arange(arr.shape[ax]).reshape(
            [-1 if i == (ax % arr.ndim) else 1 for i in range(arr.ndim)])
        idx = jnp.broadcast_to(idx, arr.shape)
        is_new = arr == vals
        inds = jax.lax.associative_scan(
            jnp.maximum, jnp.where(is_new, idx, -1), axis=ax)
        return vals, inds.astype(jnp.int32)

    return dispatch("cummin", impl, (x,), n_diff_outputs=1)


def isfinite(x, name=None):
    return unop("isfinite", jnp.isfinite, x)


def isnan(x, name=None):
    return unop("isnan", jnp.isnan, x)


def isinf(x, name=None):
    return unop("isinf", jnp.isinf, x)


# ---- linear-algebra flavoured math ----
def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return dispatch("addmm",
                    lambda i, a, b: beta * i + alpha * jnp.matmul(a, b),
                    (input, x, y))


def inner(x, y, name=None):
    return dispatch("inner", jnp.inner, (x, y))


def outer(x, y, name=None):
    return dispatch("outer",
                    lambda a, b: jnp.outer(a.reshape(-1), b.reshape(-1)),
                    (x, y))


def kron(x, y, name=None):
    return dispatch("kron", jnp.kron, (x, y))


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return dispatch("trace",
                    lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2),
                    (x,))


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    tensors = [x]
    has_prepend = prepend is not None
    has_append = append is not None
    if has_prepend:
        tensors.append(prepend)
    if has_append:
        tensors.append(append)

    def impl(a, *rest):
        i = 0
        pre = post = None
        if has_prepend:
            pre = rest[i]; i += 1
        if has_append:
            post = rest[i]
        return jnp.diff(a, n=n, axis=axis, prepend=pre, append=post)

    return dispatch("diff", impl, tuple(tensors))


def multiplex(inputs, index, name=None):
    def impl(idx, *arrays):
        stacked = jnp.stack(arrays, axis=0)
        sel = idx.reshape(-1).astype(jnp.int32)
        return stacked[sel, jnp.arange(stacked.shape[1])]

    return dispatch("multiplex", impl, (index, *inputs),
                    nondiff_mask=[True] + [False] * len(inputs))


def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        inputs = [inputs]

    def impl(*arrays):
        out = arrays[0]
        for a in arrays[1:]:
            out = out + a
        return out

    return dispatch("add_n", impl, tuple(inputs))


def inverse(x, name=None):
    return unop("inverse", jnp.linalg.inv, x)


def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    from .random import _draw
    import jax.random as jrandom
    sh = tuple(shape) if shape is not None else ()
    return _draw("log_normal",
                 lambda key: jnp.exp(mean + std * jrandom.normal(key, sh)))
