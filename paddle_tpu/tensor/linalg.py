"""Linear algebra ops (analogue of python/paddle/tensor/linalg.py).

These lower to XLA's native decompositions (cholesky/qr/svd/eigh run on TPU
via XLA custom calls or host fallback) — no cuSOLVER analogue is needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import dispatch
from ..core.tensor import Tensor
from ._helpers import normalize_axis

__all__ = [
    "matmul", "dot", "norm", "dist", "t", "cross", "cholesky",
    "cholesky_solve", "cholesky_inverse", "inv", "det", "slogdet", "svd",
    "qr", "eig", "eigh", "eigvals", "eigvalsh", "matrix_power", "matrix_rank",
    "pinv", "solve", "triangular_solve", "lstsq", "lu", "bmm", "mv",
    "multi_dot", "cond", "corrcoef", "cov", "householder_product",
    "vector_norm", "matrix_norm", "pca_lowrank",
]

from .math import matmul  # shared definition


def dot(x, y, name=None):
    def impl(a, b):
        if a.ndim == 1:
            return jnp.dot(a, b)
        return jnp.sum(a * b, axis=-1)

    return dispatch("dot", impl, (x, y))


def t(input, name=None):
    def impl(a):
        if a.ndim < 2:
            return a
        return a.T

    return dispatch("t", impl, (input,))


def norm(x, p=None, axis=None, keepdim=False, name=None):
    ax = normalize_axis(axis)

    def impl(a):
        if p is None or p == "fro":
            if ax is None:
                return jnp.sqrt(jnp.sum(jnp.square(a)))
            return jnp.linalg.norm(a, ord=None, axis=ax, keepdims=keepdim)
        if p == "nuc":
            return jnp.linalg.norm(a, ord="nuc", axis=ax, keepdims=keepdim)
        if p == float("inf"):
            base = jnp.abs(a)
            return jnp.max(base, axis=ax, keepdims=keepdim) if ax is not None or True else base
        if p == float("-inf"):
            return jnp.min(jnp.abs(a), axis=ax, keepdims=keepdim)
        if ax is None:
            return jnp.sum(jnp.abs(a) ** p) ** (1.0 / p)
        if isinstance(ax, tuple) and len(ax) == 2:
            return jnp.linalg.norm(a, ord=p, axis=ax, keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** p, axis=ax, keepdims=keepdim) ** (1.0 / p)

    return dispatch("norm", impl, (x,))


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    ax = normalize_axis(axis)

    def impl(a):
        if ax is None:
            flat = a.reshape(-1)
            out = jnp.linalg.norm(flat, ord=p)
            if keepdim:
                out = out.reshape((1,) * a.ndim)
            return out
        return jnp.linalg.vector_norm(a, ord=p, axis=ax, keepdims=keepdim)

    return dispatch("vector_norm", impl, (x,))


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    return dispatch(
        "matrix_norm",
        lambda a: jnp.linalg.matrix_norm(a, ord=p, keepdims=keepdim),
        (x,))


def dist(x, y, p=2, name=None):
    def impl(a, b):
        d = (a - b).reshape(-1)
        if p == 0:
            return jnp.sum(d != 0).astype(a.dtype)
        if p == float("inf"):
            return jnp.max(jnp.abs(d))
        if p == float("-inf"):
            return jnp.min(jnp.abs(d))
        return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)

    return dispatch("dist", impl, (x, y))


def cross(x, y, axis=9, name=None):
    def impl(a, b):
        ax = axis
        if ax == 9:  # paddle default: first axis with dim 3
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)

    return dispatch("cross", impl, (x, y))


def cholesky(x, upper=False, name=None):
    def impl(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2).conj() if upper else L

    return dispatch("cholesky", impl, (x,))


def cholesky_solve(x, y, upper=False, name=None):
    def impl(b, chol):
        L = jnp.swapaxes(chol, -1, -2).conj() if upper else chol
        z = jax.scipy.linalg.solve_triangular(L, b, lower=True)
        return jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(L, -1, -2).conj(), z, lower=False)

    return dispatch("cholesky_solve", impl, (x, y))


def cholesky_inverse(x, upper=False, name=None):
    def impl(chol):
        L = jnp.swapaxes(chol, -1, -2).conj() if upper else chol
        eye = jnp.eye(L.shape[-1], dtype=L.dtype)
        z = jax.scipy.linalg.solve_triangular(L, eye, lower=True)
        return jnp.swapaxes(z, -1, -2).conj() @ z

    return dispatch("cholesky_inverse", impl, (x,))


def inv(x, name=None):
    return dispatch("inv", jnp.linalg.inv, (x,))


def det(x, name=None):
    return dispatch("det", jnp.linalg.det, (x,))


def slogdet(x, name=None):
    def impl(a):
        sign, logabs = jnp.linalg.slogdet(a)
        return jnp.stack([sign, logabs])

    return dispatch("slogdet", impl, (x,))


def svd(x, full_matrices=False, name=None):
    """Returns (U, S, VH) with x = U @ diag(S) @ VH (reference
    python/paddle/tensor/linalg.py:2000 — VH is the conjugate transpose
    of V)."""
    def impl(a):
        return jnp.linalg.svd(a, full_matrices=full_matrices)

    return dispatch("svd", impl, (x,))


def qr(x, mode="reduced", name=None):
    def impl(a):
        if mode == "r":
            return jnp.linalg.qr(a, mode="r")
        q, r = jnp.linalg.qr(a, mode=mode)
        return q, r

    return dispatch("qr", impl, (x,))


def eig(x, name=None):
    def impl(a):
        # XLA has no general nonsymmetric eig on TPU; host callback via numpy
        import numpy as np
        if isinstance(a, jax.core.Tracer):
            raise NotImplementedError("eig requires eager mode (host LAPACK)")
        w, v = np.linalg.eig(np.asarray(a))
        return jnp.asarray(w), jnp.asarray(v)

    return dispatch("eig", impl, (x,), n_diff_outputs=0)


def eigh(x, UPLO="L", name=None):
    return dispatch("eigh",
                    lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)), (x,))


def eigvals(x, name=None):
    import numpy as np
    a = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    if isinstance(a, jax.core.Tracer):
        raise NotImplementedError("eigvals requires eager mode (host LAPACK)")
    return Tensor(jnp.asarray(np.linalg.eigvals(np.asarray(a))))


def eigvalsh(x, UPLO="L", name=None):
    return dispatch("eigvalsh", lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), (x,))


def matrix_power(x, n, name=None):
    return dispatch("matrix_power", lambda a: jnp.linalg.matrix_power(a, n), (x,))


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return dispatch(
        "matrix_rank",
        lambda a: jnp.linalg.matrix_rank(a, rtol=tol).astype(jnp.int32),
        (x,), nondiff_mask=[True])


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return dispatch("pinv", lambda a: jnp.linalg.pinv(a, rtol=rcond,
                                                      hermitian=hermitian), (x,))


def solve(x, y, name=None):
    return dispatch("solve", jnp.linalg.solve, (x, y))


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    def impl(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)

    return dispatch("triangular_solve", impl, (x, y))


def lstsq(x, y, rcond=None, driver=None, name=None):
    def impl(a, b):
        sol, res, rank_, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank_.astype(jnp.int32), sv

    return dispatch("lstsq", impl, (x, y), n_diff_outputs=1)


def lu(x, pivot=True, get_infos=False, name=None):
    def impl(a):
        lu_, piv = jax.scipy.linalg.lu_factor(a)
        info = jnp.zeros((), jnp.int32)
        if get_infos:
            return lu_, (piv + 1).astype(jnp.int32), info
        return lu_, (piv + 1).astype(jnp.int32)

    return dispatch("lu", impl, (x,), n_diff_outputs=1)


def bmm(x, y, name=None):
    return dispatch("bmm", jnp.matmul, (x, y))


def mv(x, vec, name=None):
    return dispatch("mv", jnp.matmul, (x, vec))


def multi_dot(x, name=None):
    return dispatch("multi_dot",
                    lambda *arrays: jnp.linalg.multi_dot(arrays), tuple(x))


def cond(x, p=None, name=None):
    return dispatch("cond", lambda a: jnp.linalg.cond(a, p=p), (x,))


def corrcoef(x, rowvar=True, name=None):
    return dispatch("corrcoef",
                    lambda a: jnp.corrcoef(a, rowvar=rowvar), (x,))


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    def impl(a):
        return jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0)

    return dispatch("cov", impl, (x,))


def householder_product(x, tau, name=None):
    def impl(a, t_):
        m, n = a.shape[-2], a.shape[-1]
        eye = jnp.eye(m, dtype=a.dtype)

        def body(i, q):
            v = jnp.where(jnp.arange(m) < i, 0.0, a[..., :, i].at[..., i].set(1.0))
            v = v[..., :, None]
            h = eye - t_[..., i] * (v @ jnp.swapaxes(v, -1, -2))
            return q @ h

        q = eye
        for i in range(n):
            q = body(i, q)
        return q[..., :, :n]

    return dispatch("householder_product", impl, (x, tau))


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    def impl(a):
        k = q if q is not None else min(6, a.shape[-2], a.shape[-1])
        b = a - jnp.mean(a, axis=-2, keepdims=True) if center else a
        u, s, vh = jnp.linalg.svd(b, full_matrices=False)
        return u[..., :k], s[..., :k], jnp.swapaxes(vh, -1, -2)[..., :k]

    return dispatch("pca_lowrank", impl, (x,))
