"""Search/sort ops (analogue of python/paddle/tensor/search.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import dispatch
from ..core.tensor import Tensor
from ._helpers import asarray

__all__ = [
    "argmax", "argmin", "argsort", "sort", "topk", "where", "nonzero",
    "searchsorted", "masked_select", "kthvalue", "mode", "index_sample",
    "bucketize",
]


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    from ..core.dtypes import convert_dtype
    d = convert_dtype(dtype)

    def impl(a):
        arr = a.reshape(-1) if axis is None else a
        out = jnp.argmax(arr, axis=0 if axis is None else axis, keepdims=keepdim)
        return out.astype(d)

    return dispatch("argmax", impl, (x,), nondiff_mask=[True])


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    from ..core.dtypes import convert_dtype
    d = convert_dtype(dtype)

    def impl(a):
        arr = a.reshape(-1) if axis is None else a
        out = jnp.argmin(arr, axis=0 if axis is None else axis, keepdims=keepdim)
        return out.astype(d)

    return dispatch("argmin", impl, (x,), nondiff_mask=[True])


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def impl(a):
        out = jnp.argsort(a, axis=axis, stable=stable or not descending)
        if descending:
            out = jnp.flip(out, axis=axis)
        return out.astype(jnp.int32)

    return dispatch("argsort", impl, (x,), nondiff_mask=[True])


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def impl(a):
        out = jnp.sort(a, axis=axis)
        if descending:
            out = jnp.flip(out, axis=axis)
        return out

    return dispatch("sort", impl, (x,))


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    kk = int(k.item()) if isinstance(k, Tensor) else int(k)

    def impl(a):
        ax = a.ndim - 1 if axis is None else axis % a.ndim
        moved = jnp.moveaxis(a, ax, -1)
        if largest:
            vals, idx = jax.lax.top_k(moved, kk)
        else:
            vals, idx = jax.lax.top_k(-moved, kk)
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx, -1, ax).astype(jnp.int32))

    return dispatch("topk", impl, (x,), n_diff_outputs=1)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)

    def impl(c, a, b):
        return jnp.where(c, a, b)

    return dispatch("where", impl, (condition, x, y),
                    nondiff_mask=[True, False, False])


def nonzero(x, as_tuple=False):
    # dynamic output shape: eager only
    a = asarray(x)
    if isinstance(a, jax.core.Tracer):
        raise NotImplementedError(
            "nonzero has data-dependent output shape and cannot run under jit")
    idx = np.nonzero(np.asarray(a))
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i).reshape(-1, 1)) for i in idx)
    return Tensor(jnp.asarray(np.stack(idx, axis=1).astype(np.int64)))


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    def impl(seq, v):
        side = "right" if right else "left"
        if seq.ndim == 1:
            out = jnp.searchsorted(seq, v, side=side)
        else:
            out = jax.vmap(lambda s, q: jnp.searchsorted(s, q, side=side))(
                seq.reshape(-1, seq.shape[-1]), v.reshape(-1, v.shape[-1]))
            out = out.reshape(v.shape)
        return out.astype(jnp.int32 if out_int32 else jnp.int32)

    return dispatch("searchsorted", impl, (sorted_sequence, values),
                    nondiff_mask=[True, True])


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def masked_select(x, mask, name=None):
    from .manipulation import masked_select as _ms
    return _ms(x, mask)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def impl(a):
        ax = axis % a.ndim
        svals = jnp.sort(a, axis=ax)
        sidx = jnp.argsort(a, axis=ax)
        vals = jnp.take(svals, k - 1, axis=ax)
        idx = jnp.take(sidx, k - 1, axis=ax)
        if keepdim:
            vals = jnp.expand_dims(vals, ax)
            idx = jnp.expand_dims(idx, ax)
        return vals, idx.astype(jnp.int32)

    return dispatch("kthvalue", impl, (x,), n_diff_outputs=1)


def mode(x, axis=-1, keepdim=False, name=None):
    def impl(a):
        ax = axis % a.ndim
        moved = jnp.moveaxis(a, ax, -1)
        n = moved.shape[-1]
        flat = moved.reshape(-1, n)

        def one(row):
            svals = jnp.sort(row)
            # count occurrences of each sorted value; mode = value w/ max count
            eq = svals[:, None] == svals[None, :]
            counts = eq.sum(-1)
            best = jnp.argmax(counts)  # max count; ties -> smallest value wins
            val = svals[best]
            idx = jnp.max(jnp.where(row == val, jnp.arange(n), -1))
            return val, idx

        vals, idxs = jax.vmap(one)(flat)
        vals = vals.reshape(moved.shape[:-1])
        idxs = idxs.reshape(moved.shape[:-1])
        if keepdim:
            vals = jnp.expand_dims(vals, ax)
            idxs = jnp.expand_dims(idxs, ax)
        return vals, idxs.astype(jnp.int32)

    return dispatch("mode", impl, (x,), n_diff_outputs=1)


def index_sample(x, index):
    from .manipulation import index_sample as _is
    return _is(x, index)
