"""Tensor attribute ops (analogue of python/paddle/tensor/attribute.py)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.dtypes import is_complex as _dt_is_complex
from ..core.dtypes import is_floating_point as _dt_is_float
from ..core.dtypes import is_integer as _dt_is_int
from ..core.tensor import Tensor
from ._helpers import asarray

__all__ = ["is_complex", "is_floating_point", "is_integer", "shape",
           "real", "imag"]

from .math import real, imag


def is_complex(x):
    return _dt_is_complex(asarray(x).dtype)


def is_floating_point(x):
    return _dt_is_float(asarray(x).dtype)


def is_integer(x):
    return _dt_is_int(asarray(x).dtype)


def shape(input):
    return Tensor(jnp.asarray(asarray(input).shape, dtype=jnp.int32))
