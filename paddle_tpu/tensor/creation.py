"""Creation ops (analogue of python/paddle/tensor/creation.py)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import dispatch
from ..core.dtypes import convert_dtype, default_float_dtype
from ..core.tensor import Tensor, to_tensor
from ._helpers import normalize_shape, asarray

__all__ = [
    "to_tensor", "zeros", "ones", "full", "zeros_like", "ones_like",
    "full_like", "arange", "linspace", "logspace", "eye", "empty",
    "empty_like", "diag", "diagflat", "tril_indices", "triu_indices",
    "assign", "clone", "complex", "polar", "tril", "triu", "meshgrid",
    "diag_embed", "diagonal",
]


def _dt(dtype, default=None):
    d = convert_dtype(dtype)
    if d is None:
        d = default or default_float_dtype()
    return d


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(normalize_shape(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(normalize_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        if isinstance(fill_value, bool):
            dtype = jnp.bool_
        elif isinstance(fill_value, int):
            dtype = jnp.int32
        else:
            dtype = default_float_dtype()
    return Tensor(jnp.full(normalize_shape(shape), fill_value, _dt(dtype)))


def zeros_like(x, dtype=None, name=None):
    return Tensor(jnp.zeros_like(asarray(x), dtype=convert_dtype(dtype)))


def ones_like(x, dtype=None, name=None):
    return Tensor(jnp.ones_like(asarray(x), dtype=convert_dtype(dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    return Tensor(jnp.full_like(asarray(x), fill_value, dtype=convert_dtype(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    for v in (start, end, step):
        if isinstance(v, Tensor):
            pass
    start = start.item() if isinstance(start, Tensor) else start
    end = end.item() if isinstance(end, Tensor) else end
    step = step.item() if isinstance(step, Tensor) else step
    if end is None:
        start, end = 0, start
    d = convert_dtype(dtype)
    if d is None:
        if any(isinstance(v, float) for v in (start, end, step)):
            d = default_float_dtype()
        else:
            d = jnp.int32
    return Tensor(jnp.arange(start, end, step, dtype=d))


def linspace(start, stop, num, dtype=None, name=None):
    start = start.item() if isinstance(start, Tensor) else start
    stop = stop.item() if isinstance(stop, Tensor) else stop
    num = int(num.item() if isinstance(num, Tensor) else num)
    return Tensor(jnp.linspace(start, stop, num, dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    start = start.item() if isinstance(start, Tensor) else start
    stop = stop.item() if isinstance(stop, Tensor) else stop
    num = int(num.item() if isinstance(num, Tensor) else num)
    return Tensor(jnp.logspace(start, stop, num, base=base, dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


def diag(x, offset=0, padding_value=0, name=None):
    def impl(a):
        if a.ndim == 1:
            out = jnp.diag(a, k=offset)
            if padding_value != 0:
                mask = jnp.diag(jnp.ones_like(a, dtype=jnp.bool_), k=offset)
                out = jnp.where(mask, out, padding_value)
            return out
        return jnp.diagonal(a, offset=offset)

    return dispatch("diag", impl, (x,))


def diagflat(x, offset=0, name=None):
    return dispatch("diagflat",
                    lambda a: jnp.diagflat(a.reshape(-1), k=offset), (x,))


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    def impl(a):
        n = a.shape[-1] + abs(offset)
        out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        idx = jnp.arange(a.shape[-1])
        rows = idx + max(-offset, 0)
        cols = idx + max(offset, 0)
        out = out.at[..., rows, cols].set(a)
        ndim = out.ndim
        d1, d2 = dim1 % ndim, dim2 % ndim
        perm = [i for i in range(ndim) if i not in (ndim - 2, ndim - 1)]
        # place the two new axes at dim1/dim2
        full_perm = [None] * ndim
        full_perm[d1] = ndim - 2
        full_perm[d2] = ndim - 1
        rest = iter(perm)
        for i in range(ndim):
            if full_perm[i] is None:
                full_perm[i] = next(rest)
        return jnp.transpose(out, np.argsort(full_perm))

    return dispatch("diag_embed", impl, (x,))


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return dispatch(
        "diagonal",
        lambda a: jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2),
        (x,))


def tril(x, diagonal=0, name=None):
    return dispatch("tril", lambda a: jnp.tril(a, k=diagonal), (x,))


def triu(x, diagonal=0, name=None):
    return dispatch("triu", lambda a: jnp.triu(a, k=diagonal), (x,))


def tril_indices(row, col=None, offset=0, dtype="int64"):
    col = row if col is None else col
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.stack([jnp.asarray(r), jnp.asarray(c)]).astype(convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = row if col is None else col
    r, c = np.triu_indices(row, offset, col)
    return Tensor(jnp.stack([jnp.asarray(r), jnp.asarray(c)]).astype(convert_dtype(dtype)))


def meshgrid(*args, name=None):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    return dispatch("meshgrid",
                    lambda *arrays: tuple(jnp.meshgrid(*arrays, indexing="ij")),
                    args)


def assign(x, output=None):
    src = asarray(x)
    out = dispatch("assign", lambda a: a + jnp.zeros((), a.dtype), (src,))
    if output is not None:
        output._in_place_update(out)
        return output
    return out


def clone(x, name=None):
    return x.clone() if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def complex(real, imag, name=None):
    return dispatch("complex", lambda a, b: a + 1j * b, (real, imag))


def polar(abs, angle, name=None):
    return dispatch("polar",
                    lambda r, t: r * jnp.cos(t) + 1j * r * jnp.sin(t),
                    (abs, angle))
