"""Comparison / logical / bitwise ops (analogue of python/paddle/tensor/logic.py)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import dispatch
from ..core.tensor import Tensor
from ._helpers import binop, unop, asarray

__all__ = [
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "logical_and", "logical_or", "logical_not", "logical_xor",
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "bitwise_left_shift", "bitwise_right_shift", "is_empty", "isclose",
    "allclose", "equal_all", "all", "any", "is_tensor",
]


def equal(x, y, name=None):
    return binop("equal", jnp.equal, x, y)


def not_equal(x, y, name=None):
    return binop("not_equal", jnp.not_equal, x, y)


def greater_than(x, y, name=None):
    return binop("greater_than", jnp.greater, x, y)


def greater_equal(x, y, name=None):
    return binop("greater_equal", jnp.greater_equal, x, y)


def less_than(x, y, name=None):
    return binop("less_than", jnp.less, x, y)


def less_equal(x, y, name=None):
    return binop("less_equal", jnp.less_equal, x, y)


def logical_and(x, y, out=None, name=None):
    return binop("logical_and", jnp.logical_and, x, y)


def logical_or(x, y, out=None, name=None):
    return binop("logical_or", jnp.logical_or, x, y)


def logical_xor(x, y, out=None, name=None):
    return binop("logical_xor", jnp.logical_xor, x, y)


def logical_not(x, out=None, name=None):
    return unop("logical_not", jnp.logical_not, x)


def bitwise_and(x, y, out=None, name=None):
    return binop("bitwise_and", jnp.bitwise_and, x, y)


def bitwise_or(x, y, out=None, name=None):
    return binop("bitwise_or", jnp.bitwise_or, x, y)


def bitwise_xor(x, y, out=None, name=None):
    return binop("bitwise_xor", jnp.bitwise_xor, x, y)


def bitwise_not(x, out=None, name=None):
    return unop("bitwise_not", jnp.bitwise_not, x)


def bitwise_left_shift(x, y, is_arithmetic=True, out=None, name=None):
    return binop("bitwise_left_shift", jnp.left_shift, x, y)


def bitwise_right_shift(x, y, is_arithmetic=True, out=None, name=None):
    return binop("bitwise_right_shift", jnp.right_shift, x, y)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(
        (x.size if isinstance(x, Tensor) else asarray(x).size) == 0))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return dispatch(
        "isclose",
        lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        (x, y))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return dispatch(
        "allclose",
        lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        (x, y))


def equal_all(x, y, name=None):
    a, b = asarray(x), asarray(y)
    if a.shape != b.shape:
        return Tensor(jnp.asarray(False))
    return dispatch("equal_all", lambda p, q: jnp.all(jnp.equal(p, q)), (x, y))


def all(x, axis=None, keepdim=False, name=None):
    from ._helpers import normalize_axis
    ax = normalize_axis(axis)
    return dispatch("all", lambda a: jnp.all(a, axis=ax, keepdims=keepdim), (x,))


def any(x, axis=None, keepdim=False, name=None):
    from ._helpers import normalize_axis
    ax = normalize_axis(axis)
    return dispatch("any", lambda a: jnp.any(a, axis=ax, keepdims=keepdim), (x,))


def is_tensor(x):
    return isinstance(x, Tensor)
