"""Einsum (analogue of python/paddle/tensor/einsum.py) — jnp.einsum lowers
straight onto the MXU via XLA dot_general fusion."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import dispatch

__all__ = ["einsum"]


def einsum(equation, *operands):
    return dispatch("einsum",
                    lambda *arrays: jnp.einsum(equation, *arrays), operands)
