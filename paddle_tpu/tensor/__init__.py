"""paddle_tpu.tensor — the op surface (analogue of python/paddle/tensor/).

Importing this package also monkey-patches arithmetic/method access onto
``Tensor`` (the analogue of
``python/paddle/base/dygraph/tensor_patch_methods.py``).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import dispatch
from ..core.tensor import Tensor

from .math import *  # noqa: F401,F403
from .creation import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403
from .einsum import *  # noqa: F401,F403
from .attribute import *  # noqa: F401,F403

from . import math as _math
from . import creation as _creation
from . import manipulation as _manip
from . import logic as _logic
from . import search as _search
from . import linalg as _linalg
from . import stat as _stat
from . import attribute as _attr


def _index_to_static(idx):
    """Convert Tensors inside an index expression to raw arrays."""
    def conv(i):
        if isinstance(i, Tensor):
            return i._value
        if isinstance(i, (list, np.ndarray)):
            return jnp.asarray(i)
        return i

    if isinstance(idx, tuple):
        return tuple(conv(i) for i in idx)
    return conv(idx)


def _getitem(self, idx):
    sidx = _index_to_static(idx)
    return dispatch("getitem", lambda a: a[sidx], (self,))


def _setitem(self, idx, value):
    sidx = _index_to_static(idx)
    if isinstance(value, (int, float, bool, complex)):
        out = dispatch("setitem", lambda a: a.at[sidx].set(value), (self,))
    else:
        out = dispatch("setitem",
                       lambda a, v: a.at[sidx].set(v.astype(a.dtype)),
                       (self, value))
    self._in_place_update(out)
    return self


def _rsub(self, other):
    return subtract(other, self)


def _rdiv(self, other):
    return divide(other, self)


def _rpow(self, other):
    return pow(other, self)


def _rfloordiv(self, other):
    return floor_divide(other, self)


def _rmod(self, other):
    return mod(other, self)


def _matmul_method(self, other):
    return matmul(self, other)


def _rmatmul(self, other):
    return matmul(other, self)


def _inplace(fn):
    def method(self, *args, **kwargs):
        out = fn(self, *args, **kwargs)
        self._in_place_update(out)
        return self

    return method


_BINARY = {
    "__add__": add, "__radd__": add, "__sub__": subtract, "__rsub__": _rsub,
    "__mul__": multiply, "__rmul__": multiply, "__truediv__": divide,
    "__rtruediv__": _rdiv, "__div__": divide, "__floordiv__": floor_divide,
    "__rfloordiv__": _rfloordiv, "__mod__": mod, "__rmod__": _rmod,
    "__pow__": pow, "__rpow__": _rpow, "__matmul__": _matmul_method,
    "__rmatmul__": _rmatmul, "__eq__": equal, "__ne__": not_equal,
    "__lt__": less_than, "__le__": less_equal, "__gt__": greater_than,
    "__ge__": greater_equal, "__and__": bitwise_and, "__or__": bitwise_or,
    "__xor__": bitwise_xor, "__lshift__": bitwise_left_shift,
    "__rshift__": bitwise_right_shift,
}

_METHOD_SOURCES = (_math, _manip, _logic, _search, _linalg, _stat, _attr,
                   _creation)

# methods the reference patches onto Tensor (subset that makes sense here)
_METHOD_NAMES = [
    "add", "subtract", "multiply", "divide", "floor_divide", "mod", "pow",
    "matmul", "maximum", "minimum", "exp", "log", "log2", "log10", "log1p",
    "sqrt", "rsqrt", "square", "abs", "sign", "sin", "cos", "tan", "tanh",
    "floor", "ceil", "round", "trunc", "reciprocal", "clip", "sum", "mean",
    "max", "min", "prod", "logsumexp", "cumsum", "cumprod", "isfinite",
    "isnan", "isinf", "erf", "lerp", "trace", "reshape", "transpose",
    "squeeze", "unsqueeze", "flatten", "tile", "expand", "expand_as",
    "broadcast_to", "flip", "roll", "gather", "gather_nd", "scatter",
    "scatter_", "index_select", "masked_select", "masked_fill", "split",
    "chunk", "unbind", "argmax", "argmin", "argsort", "sort", "topk",
    "nonzero", "where", "equal", "not_equal", "greater_than", "greater_equal",
    "less_than", "less_equal", "logical_and", "logical_or", "logical_not",
    "logical_xor", "all", "any", "allclose", "isclose", "equal_all", "norm",
    "dist", "dot", "cross", "cholesky", "inv", "det", "bmm", "mv", "t",
    "std", "var", "median", "quantile", "kthvalue", "mode", "tril", "triu",
    "diagonal", "numel", "take_along_axis", "put_along_axis", "unique",
    "repeat_interleave", "concat", "stack", "scale", "add_n", "neg",
    "flatten_", "reshape_", "squeeze_", "unsqueeze_", "cast_",
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "index_sample", "index_add", "index_put", "cumsum", "moveaxis",
    "is_complex", "is_floating_point",
    # elementwise / math
    "acos", "acosh", "asin", "asinh", "atan", "atan2", "atanh", "sinh",
    "cosh", "expm1", "digamma", "lgamma", "erfinv", "frac", "deg2rad",
    "rad2deg", "angle", "conj", "logit", "logaddexp", "heaviside", "hypot",
    "fmax", "fmin", "floor_mod", "remainder", "gcd", "lcm", "ldexp",
    "nan_to_num", "sgn", "stanh", "increment",
    # reductions / stats
    "amax", "amin", "count_nonzero", "cummax", "cummin", "nanmean",
    "nanmedian", "nanquantile", "nansum", "bincount", "histogram",
    # linalg
    "addmm", "cholesky_solve", "triangular_solve", "inverse", "kron",
    "inner", "outer", "matrix_power", "pinv", "qr", "svd", "eig", "eigvals",
    "slogdet", "solve", "lstsq", "lu", "cond", "matrix_rank", "multi_dot",
    "vector_norm", "matrix_norm", "corrcoef", "cov",
    # complex views
    "as_complex", "as_real", "real", "imag",
    # manipulation
    "diff", "rot90", "unflatten", "unstack", "view", "view_as", "crop",
    "slice", "strided_slice", "tensor_split", "hsplit", "vsplit", "dsplit",
    "unique_consecutive", "bucketize", "searchsorted", "multiplex",
    "scatter_nd_add", "shard_index", "is_empty", "is_integer",
    # bitwise shifts
    "bitwise_left_shift", "bitwise_right_shift",
    # random (in-place samplers + draws conditioned on self)
    "bernoulli", "multinomial", "normal_", "uniform_", "exponential_",
    "log_normal",
]


def _patch_tensor_methods():
    for name, fn in _BINARY.items():
        setattr(Tensor, name, (lambda f: lambda self, other: f(self, other))(fn))
    Tensor.__neg__ = lambda self: neg(self)
    Tensor.__abs__ = lambda self: abs(self)
    Tensor.__invert__ = lambda self: bitwise_not(self)
    Tensor.__getitem__ = _getitem
    Tensor.__setitem__ = _setitem
    for name in _METHOD_NAMES:
        fn = None
        for mod_ in _METHOD_SOURCES:
            fn = getattr(mod_, name, None)
            if fn is not None:
                break
        if fn is None:
            continue
        setattr(Tensor, name, (lambda f: lambda self, *a, **k: f(self, *a, **k))(fn))
    # in-place arithmetic sugar
    Tensor.add_ = _inplace(add)
    Tensor.subtract_ = _inplace(subtract)
    Tensor.multiply_ = _inplace(multiply)
    Tensor.divide_ = _inplace(divide)
    Tensor.clip_ = _inplace(clip)
    Tensor.scale_ = _inplace(scale)
    Tensor.tanh_ = _inplace(tanh)
    Tensor.exp_ = _inplace(exp)
    Tensor.sqrt_ = _inplace(sqrt)
    Tensor.fill_ = lambda self, v: self.set_value(
        jnp.full(self._value.shape, v, self._value.dtype))


_patch_tensor_methods()
