"""Random sampling ops (analogue of python/paddle/tensor/random.py).

All draws advance the global stateful Generator (SURVEY §2.1 RNG row); each
individual draw uses a pure counter-derived key, so a drawn op is still a pure
jax computation (safe under vjp; under jit the key is a baked constant, which
matches the reference's seed+offset capture semantics at trace time).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import jax.random as jrandom

from ..core.dispatch import dispatch
from ..core.dtypes import convert_dtype, default_float_dtype
from ..core.generator import default_generator
from ..core.tensor import Tensor
from ._helpers import normalize_shape

__all__ = [
    "uniform", "uniform_", "normal", "normal_", "standard_normal", "randn",
    "rand", "randint", "randint_like", "randperm", "bernoulli", "multinomial",
    "poisson", "exponential_", "binomial", "standard_gamma",
]


def _draw(name, sample_fn):
    key = default_generator().next_key()
    return Tensor(sample_fn(key))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    sh = normalize_shape(shape)
    d = convert_dtype(dtype) or default_float_dtype()
    return _draw("uniform",
                 lambda key: jrandom.uniform(key, sh, d, minval=min, maxval=max))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    out = uniform(x.shape, x.dtype, min, max)
    x.set_value(out)
    return x


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._value if isinstance(mean, Tensor) else mean
        s = std._value if isinstance(std, Tensor) else std
        sh = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return _draw("normal",
                     lambda key: m + s * jrandom.normal(key, sh,
                                                        default_float_dtype()))
    sh = normalize_shape(shape) if shape is not None else ()
    return _draw("normal",
                 lambda key: mean + std * jrandom.normal(key, sh,
                                                         default_float_dtype()))


def normal_(x, mean=0.0, std=1.0, name=None):
    out = normal(mean, std, x.shape)
    x.set_value(out)
    return x


def standard_normal(shape, dtype=None, name=None):
    sh = normalize_shape(shape)
    d = convert_dtype(dtype) or default_float_dtype()
    return _draw("standard_normal", lambda key: jrandom.normal(key, sh, d))


randn = standard_normal


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, 0.0, 1.0)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    sh = normalize_shape(shape)
    d = convert_dtype(dtype)
    return _draw("randint", lambda key: jrandom.randint(key, sh, low, high, d))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    return randint(low, high, x.shape, dtype or "int64")


def randperm(n, dtype="int64", name=None):
    d = convert_dtype(dtype)
    return _draw("randperm",
                 lambda key: jrandom.permutation(key, n).astype(d))


def bernoulli(x, name=None):
    key = default_generator().next_key()

    def impl(p):
        return jrandom.bernoulli(key, p).astype(p.dtype)

    return dispatch("bernoulli", impl, (x,), nondiff_mask=[True])


def multinomial(x, num_samples=1, replacement=False, name=None):
    key = default_generator().next_key()

    def impl(p):
        probs = p / jnp.sum(p, axis=-1, keepdims=True)
        if replacement:
            return jrandom.categorical(
                key, jnp.log(jnp.maximum(probs, 1e-30)),
                shape=(num_samples,) + p.shape[:-1]).T.astype(jnp.int32) \
                if p.ndim > 1 else jrandom.categorical(
                    key, jnp.log(jnp.maximum(probs, 1e-30)),
                    shape=(num_samples,)).astype(jnp.int32)
        # without replacement: gumbel top-k
        g = jrandom.gumbel(key, p.shape)
        scores = jnp.log(jnp.maximum(probs, 1e-30)) + g
        _, idx = jax.lax.top_k(scores, num_samples)
        return idx.astype(jnp.int32)

    return dispatch("multinomial", impl, (x,), nondiff_mask=[True])


def poisson(x, name=None):
    key = default_generator().next_key()
    return dispatch("poisson",
                    lambda lam: jrandom.poisson(key, lam).astype(lam.dtype),
                    (x,), nondiff_mask=[True])


def binomial(count, prob, name=None):
    key = default_generator().next_key()
    return dispatch(
        "binomial",
        lambda n, p: jrandom.binomial(key, n.astype(jnp.float32), p).astype(jnp.int32),
        (count, prob), nondiff_mask=[True, True])


def standard_gamma(x, name=None):
    key = default_generator().next_key()
    return dispatch("standard_gamma",
                    lambda a: jrandom.gamma(key, a), (x,), nondiff_mask=[True])


def exponential_(x, lam=1.0, name=None):
    key = default_generator().next_key()
    out = jrandom.exponential(key, tuple(x.shape), x.dtype) / lam
    x.set_value(out)
    return x
