"""Framework RNG helpers (analogue of python/paddle/framework/random.py)."""

from ..core.generator import (Generator, default_generator, get_rng_state,
                              seed, set_rng_state)

__all__ = ["seed", "get_rng_state", "set_rng_state", "default_generator",
           "Generator", "get_cuda_rng_state", "set_cuda_rng_state"]


def get_cuda_rng_state():  # API parity: no CUDA in this build
    return []


def set_cuda_rng_state(state):
    pass
