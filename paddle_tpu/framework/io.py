"""paddle.save/load analogue (reference python/paddle/framework/io.py:650/:893).

Tensors serialize as numpy arrays inside a pickle (protocol 4, so >4GB works
— mirroring the reference's large-object handling).  Nested dicts/lists of
Tensors (state_dicts, optimizer states) round-trip.
"""

from __future__ import annotations

import os
import pickle

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["save", "load"]

_SENTINEL = "__paddle_tpu_tensor__"
_BF16 = "__bf16__"


def _encode(obj):
    if isinstance(obj, Tensor):
        arr = obj._value
        if arr.dtype == jnp.bfloat16:
            return {_SENTINEL: True, _BF16: True,
                    "data": np.asarray(arr.astype(jnp.float32))}
        return {_SENTINEL: True, "data": np.asarray(arr)}
    if isinstance(obj, dict):
        return {k: _encode(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_encode(v) for v in obj)
    return obj


def _decode(obj):
    if isinstance(obj, dict):
        if obj.get(_SENTINEL):
            arr = jnp.asarray(obj["data"])
            if obj.get(_BF16):
                arr = arr.astype(jnp.bfloat16)
            return Tensor(arr)
        return {k: _decode(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_decode(v) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_encode(obj), f, protocol=protocol)


def load(path, **configs):
    with open(path, "rb") as f:
        return _decode(pickle.load(f))
