"""paddle_tpu.framework — serialization and framework-level utilities."""

from . import io  # noqa: F401
from . import random  # noqa: F401
