"""paddle_tpu.metric (analogue of python/paddle/metric/metrics.py)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    probs = input._value if isinstance(input, Tensor) else jnp.asarray(input)
    lbl = label._value if isinstance(label, Tensor) else jnp.asarray(label)
    if lbl.ndim == probs.ndim:
        lbl = lbl.reshape(lbl.shape[:-1])
    topk = jnp.argsort(probs, axis=-1)[..., ::-1][..., :k]
    hit = jnp.any(topk == lbl[..., None], axis=-1)
    return Tensor(jnp.mean(hit.astype(jnp.float32)))


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self._name = name or "acc"
        self.maxk = max(self.topk)
        self.reset()

    def compute(self, pred, label, *args):
        p = pred._value if isinstance(pred, Tensor) else jnp.asarray(pred)
        l = label._value if isinstance(label, Tensor) else jnp.asarray(label)
        if l.ndim == p.ndim:
            l = l[..., 0]
        topk_idx = jnp.argsort(p, axis=-1)[..., ::-1][..., :self.maxk]
        correct = topk_idx == l[..., None]
        return Tensor(correct.astype(jnp.float32))

    def update(self, correct, *args):
        c = np.asarray(correct._value if isinstance(correct, Tensor)
                       else correct)
        num = c.shape[0]
        for i, k in enumerate(self.topk):
            self.total[i] += np.any(c[..., :k], axis=-1).sum()
        self.count += num
        accs = [self.total[i] / max(self.count, 1) for i in range(len(self.topk))]
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = 0

    def accumulate(self):
        accs = [t / max(self.count, 1) for t in self.total]
        return accs[0] if len(accs) == 1 else accs

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name=None):
        self._name = name or "precision"
        self.reset()

    def update(self, preds, labels):
        p = np.asarray(preds._value if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._value if isinstance(labels, Tensor) else labels)
        p = (p > 0.5).astype(np.int32).reshape(-1)
        l = l.astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        self._name = name or "recall"
        self.reset()

    def update(self, preds, labels):
        p = np.asarray(preds._value if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._value if isinstance(labels, Tensor) else labels)
        p = (p > 0.5).astype(np.int32).reshape(-1)
        l = l.astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        self._name = name or "auc"
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        p = np.asarray(preds._value if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._value if isinstance(labels, Tensor) else labels)
        if p.ndim == 2:
            p = p[:, 1]
        p = p.reshape(-1)
        l = l.reshape(-1)
        bins = np.minimum((p * self.num_thresholds).astype(np.int64),
                          self.num_thresholds - 1)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds, dtype=np.int64)
        self._stat_neg = np.zeros(self.num_thresholds, dtype=np.int64)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        # trapezoid over thresholds from high to low
        pos = np.cumsum(self._stat_pos[::-1])
        neg = np.cumsum(self._stat_neg[::-1])
        tpr = pos / tot_pos
        fpr = neg / tot_neg
        return float(np.trapz(tpr, fpr))

    def name(self):
        return self._name
