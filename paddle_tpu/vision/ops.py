"""Vision ops — detection primitives.

Capability analogue of ``paddle.vision.ops``
(reference: python/paddle/vision/ops.py: roi_align:1107, roi_pool,
deform_conv2d:536, nms:1380, box_coder, prior_box; CUDA kernels under
paddle/phi/kernels/gpu/{roi_align_kernel.cu,deformable_conv_kernel.cu,
nms_kernel.cu}).

TPU-native design: roi_align / deform_conv2d are expressed as bilinear
gathers (differentiable, static-shape, XLA-fusable — the TPU analogue of
the reference's hand-written CUDA bilinear kernels).  NMS is inherently
data-dependent, so it runs as an eager host op returning kept indices
(like the reference's dynamic-shape outputs, it is eager-only and
non-differentiable).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import dispatch
from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn.layer.layers import Layer

__all__ = ["roi_align", "RoIAlign", "roi_pool", "RoIPool", "nms",
           "deform_conv2d", "DeformConv2D", "box_coder", "prior_box",
           "matrix_nms"]


def _bilinear_sample(feat, ys, xs):
    """feat [C, H, W]; ys/xs arbitrary same-shaped float grids -> [C, *grid].

    Out-of-range samples clamp to the border (reference roi_align
    behavior: sample points outside the image are clipped)."""
    H, W = feat.shape[-2], feat.shape[-1]
    y0 = jnp.clip(jnp.floor(ys), 0, H - 1)
    x0 = jnp.clip(jnp.floor(xs), 0, W - 1)
    y1 = jnp.clip(y0 + 1, 0, H - 1)
    x1 = jnp.clip(x0 + 1, 0, W - 1)
    ly = jnp.clip(ys - y0, 0.0, 1.0)
    lx = jnp.clip(xs - x0, 0.0, 1.0)
    y0i, y1i = y0.astype(jnp.int32), y1.astype(jnp.int32)
    x0i, x1i = x0.astype(jnp.int32), x1.astype(jnp.int32)

    def gather(yi, xi):
        return feat[:, yi, xi]  # advanced indexing broadcasts over grid

    v00 = gather(y0i, x0i)
    v01 = gather(y0i, x1i)
    v10 = gather(y1i, x0i)
    v11 = gather(y1i, x1i)
    return ((1 - ly) * (1 - lx) * v00 + (1 - ly) * lx * v01 +
            ly * (1 - lx) * v10 + ly * lx * v11)


def _bilinear_sample_zero(feat, ys, xs):
    """Like _bilinear_sample but out-of-range corners contribute zero
    (deformable-conv reference semantics, dmcn_im2col_bilinear: each of
    the four corners outside the map is dropped, and fully-outside points
    vanish entirely)."""
    H, W = feat.shape[-2], feat.shape[-1]
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    y1 = y0 + 1
    x1 = x0 + 1
    ly = ys - y0
    lx = xs - x0

    def corner(yc, xc, w):
        valid = ((yc >= 0) & (yc <= H - 1) & (xc >= 0) & (xc <= W - 1))
        yi = jnp.clip(yc, 0, H - 1).astype(jnp.int32)
        xi = jnp.clip(xc, 0, W - 1).astype(jnp.int32)
        return feat[:, yi, xi] * (w * valid.astype(feat.dtype))

    return (corner(y0, x0, (1 - ly) * (1 - lx)) +
            corner(y0, x1, (1 - ly) * lx) +
            corner(y1, x0, ly * (1 - lx)) +
            corner(y1, x1, ly * lx))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (Mask R-CNN): averages bilinear samples in each output bin.

    x: [N, C, H, W]; boxes: [R, 4] (x1, y1, x2, y2); boxes_num: [N] rois
    per image (prefix-assignment, reference semantics).
    """
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    def impl(xa, ba, bna):
        roi_img = jnp.repeat(jnp.arange(bna.shape[0]), bna,
                             total_repeat_length=ba.shape[0])
        offset = 0.5 if aligned else 0.0
        x1 = ba[:, 0] * spatial_scale - offset
        y1 = ba[:, 1] * spatial_scale - offset
        x2 = ba[:, 2] * spatial_scale - offset
        y2 = ba[:, 3] * spatial_scale - offset
        rw = x2 - x1
        rh = y2 - y1
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        ns = sampling_ratio if sampling_ratio > 0 else 2
        # sample grid per roi: [ph*ns] x [pw*ns] points
        iy = (jnp.arange(ph * ns) + 0.5) / ns  # in bin-h units
        ix = (jnp.arange(pw * ns) + 0.5) / ns

        def one_roi(img_idx, yy1, xx1, bh, bw):
            ys = yy1 + iy * bh                      # [ph*ns]
            xs = xx1 + ix * bw                      # [pw*ns]
            grid_y = jnp.broadcast_to(ys[:, None], (ph * ns, pw * ns))
            grid_x = jnp.broadcast_to(xs[None, :], (ph * ns, pw * ns))
            vals = _bilinear_sample(xa[img_idx], grid_y, grid_x)
            c = vals.shape[0]
            vals = vals.reshape(c, ph, ns, pw, ns)
            return vals.mean(axis=(2, 4))           # [C, ph, pw]

        import jax
        return jax.vmap(one_roi)(roi_img, y1, x1, bin_h, bin_w)

    return dispatch("roi_align", impl, (x, boxes, boxes_num),
                    nondiff_mask=[False, True, True])


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """RoIPool (Fast R-CNN): max over quantized bins.  Expressed as a dense
    sample-then-max (static shapes; the reference maxes over the integer
    cells of each bin, we max over a fixed 4x-oversampled grid per bin —
    sub-pixel spacing for bins up to 4 px wide)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    ns = 4

    def impl(xa, ba, bna):
        roi_img = jnp.repeat(jnp.arange(bna.shape[0]), bna,
                             total_repeat_length=ba.shape[0])
        x1 = jnp.round(ba[:, 0] * spatial_scale)
        y1 = jnp.round(ba[:, 1] * spatial_scale)
        x2 = jnp.round(ba[:, 2] * spatial_scale)
        y2 = jnp.round(ba[:, 3] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        iy = (jnp.arange(ph * ns) + 0.5) / ns
        ix = (jnp.arange(pw * ns) + 0.5) / ns

        def one_roi(img_idx, yy1, xx1, bh, bw):
            ys = yy1 + iy * bh
            xs = xx1 + ix * bw
            grid_y = jnp.broadcast_to(ys[:, None], (ph * ns, pw * ns))
            grid_x = jnp.broadcast_to(xs[None, :], (ph * ns, pw * ns))
            vals = _bilinear_sample(xa[img_idx], grid_y, grid_x)
            c = vals.shape[0]
            vals = vals.reshape(c, ph, ns, pw, ns)
            return vals.max(axis=(2, 4))

        import jax
        return jax.vmap(one_roi)(roi_img, y1, x1, bin_h, bin_w)

    return dispatch("roi_pool", impl, (x, boxes, boxes_num),
                    nondiff_mask=[False, True, True])


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        self._spatial_scale)


def _iou_matrix(boxes):
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = np.maximum(x2 - x1, 0) * np.maximum(y2 - y1, 0)
    xx1 = np.maximum(x1[:, None], x1[None, :])
    yy1 = np.maximum(y1[:, None], y1[None, :])
    xx2 = np.minimum(x2[:, None], x2[None, :])
    yy2 = np.minimum(y2[:, None], y2[None, :])
    inter = np.maximum(xx2 - xx1, 0) * np.maximum(yy2 - yy1, 0)
    union = area[:, None] + area[None, :] - inter
    return inter / np.maximum(union, 1e-10)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Hard NMS.  Eager host op (dynamic output shape, like the
    reference's nms_kernel); returns kept indices sorted by score."""
    b = np.asarray(boxes._value if isinstance(boxes, Tensor) else boxes,
                   np.float32)
    n = b.shape[0]
    s = (np.asarray(scores._value if isinstance(scores, Tensor) else scores,
                    np.float32) if scores is not None
         else np.arange(n, 0, -1, dtype=np.float32))
    if category_idxs is not None:
        # category-aware: offset boxes per category so they never overlap
        cidx = np.asarray(category_idxs._value
                          if isinstance(category_idxs, Tensor)
                          else category_idxs)
        max_coord = b.max() if n else 0.0
        b = b + (cidx[:, None].astype(np.float32) * (max_coord + 1.0))
    order = np.argsort(-s)
    iou = _iou_matrix(b)
    keep = []
    suppressed = np.zeros(n, bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        suppressed |= iou[i] > iou_threshold
        suppressed[i] = True
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=-1, keep_top_k=-1, use_gaussian=False,
               gaussian_sigma=2.0, name=None):
    """Matrix NMS (SOLOv2): soft decay of scores by pairwise IoU.
    Single-image [N, 4] boxes + [N] scores variant; returns
    (decayed_scores, kept_indices)."""
    b = np.asarray(bboxes._value if isinstance(bboxes, Tensor) else bboxes,
                   np.float32)
    s = np.asarray(scores._value if isinstance(scores, Tensor) else scores,
                   np.float32)
    valid = np.nonzero(s >= score_threshold)[0]
    if nms_top_k > 0:
        valid = valid[np.argsort(-s[valid])[:nms_top_k]]
    else:
        valid = valid[np.argsort(-s[valid])]
    if valid.size == 0:
        return Tensor(jnp.zeros((0,), jnp.float32)), \
            Tensor(jnp.zeros((0,), jnp.int64))
    bb, ss = b[valid], s[valid]
    iou = np.triu(_iou_matrix(bb), k=1)
    # compensate IoU: for each box (as suppressor i), its own max IoU with
    # any higher-scored box — row-indexed in the decay matrix (SOLOv2 eq. 4)
    iou_cmax = iou.max(axis=0)
    if use_gaussian:
        decay = np.exp(-(iou ** 2 - iou_cmax[:, None] ** 2) / gaussian_sigma)
        decay = decay.min(axis=0)
    else:
        decay = ((1 - iou) / np.maximum(1 - iou_cmax[:, None], 1e-10)) \
            .min(axis=0)
    decay = np.minimum(decay, 1.0)
    decayed = ss * decay
    mask = decayed >= post_threshold
    out_idx = valid[mask]
    out_scores = decayed[mask]
    order = np.argsort(-out_scores)
    if keep_top_k > 0:
        order = order[:keep_top_k]
    return Tensor(jnp.asarray(out_scores[order])), \
        Tensor(jnp.asarray(out_idx[order].astype(np.int64)))


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 as bilinear-gather + matmul.

    x [N, Cin, H, W]; offset [N, 2*dg*kh*kw, Ho, Wo] ((dy, dx) pairs);
    mask [N, dg*kh*kw, Ho, Wo] for v2 modulation; weight
    [Cout, Cin/groups, kh, kw].
    """
    if groups != 1 or deformable_groups != 1:
        raise NotImplementedError(
            "deform_conv2d: groups/deformable_groups > 1 not supported yet")
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    padding = (padding, padding) if isinstance(padding, int) \
        else tuple(padding)
    dilation = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)

    kh, kw = weight.shape[2], weight.shape[3]
    tensors = [x, offset, weight]
    has_mask = mask is not None
    if has_mask:
        tensors.append(mask)
    if bias is not None:
        tensors.append(bias)

    def impl(xa, off, wa, *rest):
        import jax
        r = list(rest)
        ma = r.pop(0) if has_mask else None
        ba = r.pop(0) if (bias is not None) else None
        N, C, H, W = xa.shape
        Ho = (H + 2 * padding[0] - dilation[0] * (kh - 1) - 1) \
            // stride[0] + 1
        Wo = (W + 2 * padding[1] - dilation[1] * (kw - 1) - 1) \
            // stride[1] + 1
        xa_p = jnp.pad(xa, ((0, 0), (0, 0),
                            (padding[0], padding[0]),
                            (padding[1], padding[1])))
        # base sampling locations per (k, out-pixel), in padded coords
        oy = jnp.arange(Ho) * stride[0]
        ox = jnp.arange(Wo) * stride[1]
        ky = jnp.arange(kh) * dilation[0]
        kx = jnp.arange(kw) * dilation[1]
        base_y = oy[None, :, None] + ky[:, None, None]    # [kh, Ho, 1]
        base_x = ox[None, None, :] + kx[:, None, None]    # [kw, 1, Wo] via kx
        # offsets: [N, 2*kh*kw, Ho, Wo] -> dy/dx [N, kh*kw, Ho, Wo]
        off = off.reshape(N, kh * kw, 2, Ho, Wo)
        dy, dx = off[:, :, 0], off[:, :, 1]
        ys = (base_y.reshape(kh, 1, Ho, 1) +
              jnp.zeros((1, kw, 1, Wo))).reshape(1, kh * kw, Ho, Wo) + dy
        xs = (jnp.zeros((kh, 1, Ho, 1)) +
              base_x.reshape(1, kw, 1, Wo)).reshape(1, kh * kw, Ho, Wo) + dx

        def per_image(feat, ysi, xsi, mi):
            vals = _bilinear_sample_zero(feat, ysi, xsi)  # [C,kh*kw,Ho,Wo]
            if mi is not None:
                vals = vals * mi[None]
            return vals

        vals = jax.vmap(per_image)(
            xa_p, ys, xs,
            ma.reshape(N, kh * kw, Ho, Wo) if ma is not None else
            jnp.ones((N, kh * kw, Ho, Wo), xa.dtype))
        # contract [C*kh*kw] with weight [Cout, C*kh*kw]
        cols = vals.reshape(N, C * kh * kw, Ho * Wo)
        wmat = wa.reshape(wa.shape[0], C * kh * kw)
        out = jnp.einsum("ok,nkp->nop", wmat, cols).reshape(
            N, wa.shape[0], Ho, Wo)
        if ba is not None:
            out = out + ba[None, :, None, None]
        return out

    return dispatch("deform_conv2d", impl, tensors)


class DeformConv2D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        from ..nn.initializer import XavierNormal
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, ks[0], ks[1]),
            attr=weight_attr, default_initializer=XavierNormal())
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias, self._stride,
                             self._padding, self._dilation,
                             self._deformable_groups, self._groups, mask)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Encode/decode boxes against priors (reference box_coder op)."""
    norm = 0.0 if box_normalized else 1.0

    def impl(pb, pbv, tb):
        pw = pb[:, 2] - pb[:, 0] + norm
        phh = pb[:, 3] - pb[:, 1] + norm
        pcx = pb[:, 0] + pw * 0.5
        pcy = pb[:, 1] + phh * 0.5
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tcx = tb[:, 0] + tw * 0.5
            tcy = tb[:, 1] + th * 0.5
            dx = (tcx - pcx) / pw / pbv[:, 0]
            dy = (tcy - pcy) / phh / pbv[:, 1]
            dw = jnp.log(tw / pw) / pbv[:, 2]
            dh = jnp.log(th / phh) / pbv[:, 3]
            return jnp.stack([dx, dy, dw, dh], axis=-1)
        # decode_center_size: tb holds deltas
        dcx = pbv[:, 0] * tb[..., 0] * pw + pcx
        dcy = pbv[:, 1] * tb[..., 1] * phh + pcy
        dw = jnp.exp(pbv[:, 2] * tb[..., 2]) * pw
        dh = jnp.exp(pbv[:, 3] * tb[..., 3]) * phh
        return jnp.stack([dcx - dw * 0.5, dcy - dh * 0.5,
                          dcx + dw * 0.5 - norm, dcy + dh * 0.5 - norm],
                         axis=-1)

    return dispatch("box_coder", impl, (prior_box, prior_box_var, target_box),
                    nondiff_mask=[True, True, False])


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None):
    """SSD prior (anchor) boxes over a feature map (reference prior_box)."""
    fh, fw = int(input.shape[2]), int(input.shape[3])
    ih, iw = float(image.shape[2]), float(image.shape[3])
    step_h = steps[1] or ih / fh
    step_w = steps[0] or iw / fw
    ars = list(aspect_ratios)
    if flip:
        ars += [1.0 / a for a in aspect_ratios if a != 1.0]

    boxes = []
    for ms in min_sizes:
        for ar in ars:
            w = ms * np.sqrt(ar)
            h = ms / np.sqrt(ar)
            boxes.append((w, h))
        if max_sizes:
            for mx in max_sizes:
                s = np.sqrt(ms * mx)
                boxes.append((s, s))
    k = len(boxes)
    cy = (np.arange(fh) + offset) * step_h
    cx = (np.arange(fw) + offset) * step_w
    grid_cx, grid_cy = np.meshgrid(cx, cy)
    out = np.zeros((fh, fw, k, 4), np.float32)
    for i, (w, h) in enumerate(boxes):
        out[:, :, i, 0] = (grid_cx - w / 2) / iw
        out[:, :, i, 1] = (grid_cy - h / 2) / ih
        out[:, :, i, 2] = (grid_cx + w / 2) / iw
        out[:, :, i, 3] = (grid_cy + h / 2) / ih
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).copy()
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(var))
