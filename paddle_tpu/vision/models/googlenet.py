"""GoogLeNet / Inception-v1 (analogue of
python/paddle/vision/models/googlenet.py)."""

from __future__ import annotations

from ...tensor.manipulation import concat
from ... import nn

__all__ = ["GoogLeNet", "googlenet"]


class ConvBlock(nn.Sequential):
    def __init__(self, in_channels, out_channels, **kwargs):
        super().__init__(
            nn.Conv2D(in_channels, out_channels, bias_attr=False, **kwargs),
            nn.BatchNorm2D(out_channels),
            nn.ReLU())


class Inception(nn.Layer):
    def __init__(self, in_channels, ch1x1, ch3x3red, ch3x3, ch5x5red, ch5x5,
                 pool_proj):
        super().__init__()
        self.branch1 = ConvBlock(in_channels, ch1x1, kernel_size=1)
        self.branch2 = nn.Sequential(
            ConvBlock(in_channels, ch3x3red, kernel_size=1),
            ConvBlock(ch3x3red, ch3x3, kernel_size=3, padding=1))
        self.branch3 = nn.Sequential(
            ConvBlock(in_channels, ch5x5red, kernel_size=1),
            ConvBlock(ch5x5red, ch5x5, kernel_size=3, padding=1))
        self.branch4 = nn.Sequential(
            nn.MaxPool2D(3, stride=1, padding=1),
            ConvBlock(in_channels, pool_proj, kernel_size=1))

    def forward(self, x):
        return concat([self.branch1(x), self.branch2(x), self.branch3(x),
                       self.branch4(x)], axis=1)


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.conv1 = ConvBlock(3, 64, kernel_size=7, stride=2, padding=3)
        self.maxpool1 = nn.MaxPool2D(3, stride=2, padding=1)
        self.conv2 = ConvBlock(64, 64, kernel_size=1)
        self.conv3 = ConvBlock(64, 192, kernel_size=3, padding=1)
        self.maxpool2 = nn.MaxPool2D(3, stride=2, padding=1)

        self.inception3a = Inception(192, 64, 96, 128, 16, 32, 32)
        self.inception3b = Inception(256, 128, 128, 192, 32, 96, 64)
        self.maxpool3 = nn.MaxPool2D(3, stride=2, padding=1)
        self.inception4a = Inception(480, 192, 96, 208, 16, 48, 64)
        self.inception4b = Inception(512, 160, 112, 224, 24, 64, 64)
        self.inception4c = Inception(512, 128, 128, 256, 24, 64, 64)
        self.inception4d = Inception(512, 112, 144, 288, 32, 64, 64)
        self.inception4e = Inception(528, 256, 160, 320, 32, 128, 128)
        self.maxpool4 = nn.MaxPool2D(3, stride=2, padding=1)
        self.inception5a = Inception(832, 256, 160, 320, 32, 128, 128)
        self.inception5b = Inception(832, 384, 192, 384, 48, 128, 128)

        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.2)
            self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.maxpool1(self.conv1(x))
        x = self.maxpool2(self.conv3(self.conv2(x)))
        x = self.inception3b(self.inception3a(x))
        x = self.maxpool3(x)
        x = self.inception4e(self.inception4d(self.inception4c(
            self.inception4b(self.inception4a(x)))))
        x = self.maxpool4(x)
        x = self.inception5b(self.inception5a(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(self.dropout(x))
        return x


def googlenet(pretrained=False, **kwargs):
    return GoogLeNet(**kwargs)
