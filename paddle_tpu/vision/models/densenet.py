"""DenseNet (analogue of python/paddle/vision/models/densenet.py)."""

from __future__ import annotations

from ...tensor.manipulation import concat
from ... import nn

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]


class DenseLayer(nn.Layer):
    def __init__(self, num_input_features, growth_rate, bn_size, drop_rate):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(num_input_features)
        self.relu1 = nn.ReLU()
        self.conv1 = nn.Conv2D(num_input_features, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.relu2 = nn.ReLU()
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.drop_rate = drop_rate
        if drop_rate > 0:
            self.dropout = nn.Dropout(drop_rate)

    def forward(self, x):
        out = self.conv1(self.relu1(self.norm1(x)))
        out = self.conv2(self.relu2(self.norm2(out)))
        if self.drop_rate > 0:
            out = self.dropout(out)
        return concat([x, out], axis=1)


class DenseBlock(nn.Layer):
    def __init__(self, num_layers, num_input_features, bn_size, growth_rate,
                 drop_rate):
        super().__init__()
        self.layers = nn.LayerList([
            DenseLayer(num_input_features + i * growth_rate, growth_rate,
                       bn_size, drop_rate)
            for i in range(num_layers)])

    def forward(self, x):
        for layer in self.layers:
            x = layer(x)
        return x


class Transition(nn.Sequential):
    def __init__(self, num_input_features, num_output_features):
        super().__init__(
            nn.BatchNorm2D(num_input_features), nn.ReLU(),
            nn.Conv2D(num_input_features, num_output_features, 1,
                      bias_attr=False),
            nn.AvgPool2D(2, stride=2))


_LAYER_CFG = {
    121: (32, [6, 12, 24, 16], 64),
    161: (48, [6, 12, 36, 24], 96),
    169: (32, [6, 12, 32, 32], 64),
    201: (32, [6, 12, 48, 32], 64),
    264: (32, [6, 12, 64, 48], 64),
}


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        growth_rate, block_config, num_init_features = _LAYER_CFG[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.conv1 = nn.Sequential(
            nn.Conv2D(3, num_init_features, 7, stride=2, padding=3,
                      bias_attr=False),
            nn.BatchNorm2D(num_init_features), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1))

        blocks = []
        num_features = num_init_features
        for i, num_layers in enumerate(block_config):
            blocks.append(DenseBlock(num_layers, num_features, bn_size,
                                     growth_rate, dropout))
            num_features += num_layers * growth_rate
            if i != len(block_config) - 1:
                blocks.append(Transition(num_features, num_features // 2))
                num_features //= 2
        self.blocks = nn.Sequential(*blocks)
        self.norm_final = nn.BatchNorm2D(num_features)
        self.relu_final = nn.ReLU()
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(num_features, num_classes)

    def forward(self, x):
        x = self.conv1(x)
        x = self.blocks(x)
        x = self.relu_final(self.norm_final(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


def _densenet(layers, **kwargs):
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, **kwargs)
