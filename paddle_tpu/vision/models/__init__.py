"""Vision models (analogue of python/paddle/vision/models/)."""

from .resnet import (ResNet, resnet18, resnet34, resnet50, resnet101,
                     resnet152, wide_resnet50_2, wide_resnet101_2)
from .lenet import LeNet

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
           "resnet152", "wide_resnet50_2", "wide_resnet101_2", "LeNet"]
