"""Vision models (analogue of python/paddle/vision/models/)."""

from .resnet import (ResNet, resnet18, resnet34, resnet50, resnet101,
                     resnet152, wide_resnet50_2, wide_resnet101_2)
from .lenet import LeNet
from .alexnet import AlexNet, alexnet
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19
from .mobilenetv1 import MobileNetV1, mobilenet_v1
from .mobilenetv2 import MobileNetV2, mobilenet_v2
from .mobilenetv3 import (MobileNetV3Small, MobileNetV3Large,
                          mobilenet_v3_small, mobilenet_v3_large)
from .squeezenet import SqueezeNet, squeezenet1_0, squeezenet1_1
from .densenet import (DenseNet, densenet121, densenet161, densenet169,
                       densenet201, densenet264)
from .googlenet import GoogLeNet, googlenet
from .inceptionv3 import InceptionV3, inception_v3
from .shufflenetv2 import (ShuffleNetV2, shufflenet_v2_x0_25,
                           shufflenet_v2_x0_33, shufflenet_v2_x0_5,
                           shufflenet_v2_x1_0, shufflenet_v2_x1_5,
                           shufflenet_v2_x2_0, shufflenet_v2_swish)

__all__ = [
    "ResNet", "resnet18", "resnet34", "resnet50", "resnet101", "resnet152",
    "wide_resnet50_2", "wide_resnet101_2", "LeNet",
    "AlexNet", "alexnet",
    "VGG", "vgg11", "vgg13", "vgg16", "vgg19",
    "MobileNetV1", "mobilenet_v1", "MobileNetV2", "mobilenet_v2",
    "MobileNetV3Small", "MobileNetV3Large", "mobilenet_v3_small",
    "mobilenet_v3_large",
    "SqueezeNet", "squeezenet1_0", "squeezenet1_1",
    "DenseNet", "densenet121", "densenet161", "densenet169", "densenet201",
    "densenet264",
    "GoogLeNet", "googlenet",
    "InceptionV3", "inception_v3",
    "ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
    "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
    "shufflenet_v2_x2_0", "shufflenet_v2_swish",
]
