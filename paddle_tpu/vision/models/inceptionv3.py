"""Inception-v3 (analogue of python/paddle/vision/models/inceptionv3.py)."""

from __future__ import annotations

from ...tensor.manipulation import concat
from ... import nn

__all__ = ["InceptionV3", "inception_v3"]


class ConvBNLayer(nn.Sequential):
    def __init__(self, in_c, out_c, kernel_size, stride=1, padding=0):
        super().__init__(
            nn.Conv2D(in_c, out_c, kernel_size, stride=stride,
                      padding=padding, bias_attr=False),
            nn.BatchNorm2D(out_c),
            nn.ReLU())


class InceptionA(nn.Layer):
    def __init__(self, in_c, pool_features):
        super().__init__()
        self.branch1x1 = ConvBNLayer(in_c, 64, 1)
        self.branch5x5 = nn.Sequential(ConvBNLayer(in_c, 48, 1),
                                       ConvBNLayer(48, 64, 5, padding=2))
        self.branch3x3dbl = nn.Sequential(
            ConvBNLayer(in_c, 64, 1), ConvBNLayer(64, 96, 3, padding=1),
            ConvBNLayer(96, 96, 3, padding=1))
        self.branch_pool = nn.Sequential(
            nn.AvgPool2D(3, stride=1, padding=1),
            ConvBNLayer(in_c, pool_features, 1))

    def forward(self, x):
        return concat([self.branch1x1(x), self.branch5x5(x),
                       self.branch3x3dbl(x), self.branch_pool(x)], axis=1)


class InceptionB(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.branch3x3 = ConvBNLayer(in_c, 384, 3, stride=2)
        self.branch3x3dbl = nn.Sequential(
            ConvBNLayer(in_c, 64, 1), ConvBNLayer(64, 96, 3, padding=1),
            ConvBNLayer(96, 96, 3, stride=2))
        self.branch_pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.branch3x3(x), self.branch3x3dbl(x),
                       self.branch_pool(x)], axis=1)


class InceptionC(nn.Layer):
    def __init__(self, in_c, channels_7x7):
        super().__init__()
        c7 = channels_7x7
        self.branch1x1 = ConvBNLayer(in_c, 192, 1)
        self.branch7x7 = nn.Sequential(
            ConvBNLayer(in_c, c7, 1),
            ConvBNLayer(c7, c7, (1, 7), padding=(0, 3)),
            ConvBNLayer(c7, 192, (7, 1), padding=(3, 0)))
        self.branch7x7dbl = nn.Sequential(
            ConvBNLayer(in_c, c7, 1),
            ConvBNLayer(c7, c7, (7, 1), padding=(3, 0)),
            ConvBNLayer(c7, c7, (1, 7), padding=(0, 3)),
            ConvBNLayer(c7, c7, (7, 1), padding=(3, 0)),
            ConvBNLayer(c7, 192, (1, 7), padding=(0, 3)))
        self.branch_pool = nn.Sequential(
            nn.AvgPool2D(3, stride=1, padding=1), ConvBNLayer(in_c, 192, 1))

    def forward(self, x):
        return concat([self.branch1x1(x), self.branch7x7(x),
                       self.branch7x7dbl(x), self.branch_pool(x)], axis=1)


class InceptionD(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.branch3x3 = nn.Sequential(ConvBNLayer(in_c, 192, 1),
                                       ConvBNLayer(192, 320, 3, stride=2))
        self.branch7x7x3 = nn.Sequential(
            ConvBNLayer(in_c, 192, 1),
            ConvBNLayer(192, 192, (1, 7), padding=(0, 3)),
            ConvBNLayer(192, 192, (7, 1), padding=(3, 0)),
            ConvBNLayer(192, 192, 3, stride=2))
        self.branch_pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.branch3x3(x), self.branch7x7x3(x),
                       self.branch_pool(x)], axis=1)


class InceptionE(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.branch1x1 = ConvBNLayer(in_c, 320, 1)
        self.branch3x3_1 = ConvBNLayer(in_c, 384, 1)
        self.branch3x3_2a = ConvBNLayer(384, 384, (1, 3), padding=(0, 1))
        self.branch3x3_2b = ConvBNLayer(384, 384, (3, 1), padding=(1, 0))
        self.branch3x3dbl_1 = nn.Sequential(
            ConvBNLayer(in_c, 448, 1), ConvBNLayer(448, 384, 3, padding=1))
        self.branch3x3dbl_2a = ConvBNLayer(384, 384, (1, 3), padding=(0, 1))
        self.branch3x3dbl_2b = ConvBNLayer(384, 384, (3, 1), padding=(1, 0))
        self.branch_pool = nn.Sequential(
            nn.AvgPool2D(3, stride=1, padding=1), ConvBNLayer(in_c, 192, 1))

    def forward(self, x):
        b3 = self.branch3x3_1(x)
        b3 = concat([self.branch3x3_2a(b3), self.branch3x3_2b(b3)], axis=1)
        bd = self.branch3x3dbl_1(x)
        bd = concat([self.branch3x3dbl_2a(bd), self.branch3x3dbl_2b(bd)],
                    axis=1)
        return concat([self.branch1x1(x), b3, bd, self.branch_pool(x)],
                      axis=1)


class InceptionV3(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.inception_stem = nn.Sequential(
            ConvBNLayer(3, 32, 3, stride=2),
            ConvBNLayer(32, 32, 3),
            ConvBNLayer(32, 64, 3, padding=1),
            nn.MaxPool2D(3, stride=2),
            ConvBNLayer(64, 80, 1),
            ConvBNLayer(80, 192, 3),
            nn.MaxPool2D(3, stride=2))
        self.inception_block_list = nn.Sequential(
            InceptionA(192, 32), InceptionA(256, 64), InceptionA(288, 64),
            InceptionB(288),
            InceptionC(768, 128), InceptionC(768, 160), InceptionC(768, 160),
            InceptionC(768, 192),
            InceptionD(768),
            InceptionE(1280), InceptionE(2048))
        if with_pool:
            self.avg_pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.2)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.inception_stem(x)
        x = self.inception_block_list(x)
        if self.with_pool:
            x = self.avg_pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(self.dropout(x))
        return x


def inception_v3(pretrained=False, **kwargs):
    return InceptionV3(**kwargs)
