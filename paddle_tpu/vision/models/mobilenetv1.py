"""MobileNetV1 (analogue of python/paddle/vision/models/mobilenetv1.py)."""

from __future__ import annotations

from ... import nn

__all__ = ["MobileNetV1", "mobilenet_v1"]


class ConvBNLayer(nn.Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, num_groups=1):
        super().__init__()
        self.conv = nn.Conv2D(in_channels, out_channels, kernel_size,
                              stride=stride, padding=padding,
                              groups=num_groups, bias_attr=False)
        self.norm = nn.BatchNorm2D(out_channels)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.norm(self.conv(x)))


class DepthwiseSeparable(nn.Layer):
    def __init__(self, in_channels, out_channels1, out_channels2, num_groups,
                 stride, scale):
        super().__init__()
        self.depthwise = ConvBNLayer(in_channels, int(out_channels1 * scale),
                                     3, stride=stride, padding=1,
                                     num_groups=int(num_groups * scale))
        self.pointwise = ConvBNLayer(int(out_channels1 * scale),
                                     int(out_channels2 * scale), 1)

    def forward(self, x):
        return self.pointwise(self.depthwise(x))


class MobileNetV1(nn.Layer):
    """scale: width multiplier; num_classes<=0 drops the classifier head."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.conv1 = ConvBNLayer(3, int(32 * scale), 3, stride=2, padding=1)
        # (in, out1, out2, groups, stride)
        cfg = [(32, 32, 64, 32, 1), (64, 64, 128, 64, 2),
               (128, 128, 128, 128, 1), (128, 128, 256, 128, 2),
               (256, 256, 256, 256, 1), (256, 256, 512, 256, 2),
               (512, 512, 512, 512, 1), (512, 512, 512, 512, 1),
               (512, 512, 512, 512, 1), (512, 512, 512, 512, 1),
               (512, 512, 512, 512, 1), (512, 512, 1024, 512, 2),
               (1024, 1024, 1024, 1024, 1)]
        blocks = []
        for in_c, o1, o2, g, s in cfg:
            blocks.append(DepthwiseSeparable(int(in_c * scale), o1, o2, g, s,
                                             scale))
        self.blocks = nn.Sequential(*blocks)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(int(1024 * scale), num_classes)

    def forward(self, x):
        x = self.conv1(x)
        x = self.blocks(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)
