"""AlexNet (analogue of python/paddle/vision/models/alexnet.py)."""

from __future__ import annotations

from ... import nn

__all__ = ["AlexNet", "alexnet"]


class AlexNet(nn.Layer):
    def __init__(self, num_classes=1000):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, stride=2))
        self.avgpool = nn.AdaptiveAvgPool2D((6, 6))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(), nn.Linear(256 * 6 * 6, 4096), nn.ReLU(),
                nn.Dropout(), nn.Linear(4096, 4096), nn.ReLU(),
                nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.features(x)
        x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


def alexnet(pretrained=False, **kwargs):
    return AlexNet(**kwargs)
