"""ShuffleNetV2 (analogue of python/paddle/vision/models/shufflenetv2.py)."""

from __future__ import annotations

from ...tensor.manipulation import concat, split
from ... import nn

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
           "shufflenet_v2_x2_0", "shufflenet_v2_swish"]


def _act(name):
    return nn.Swish() if name == "swish" else nn.ReLU()


class ConvBNAct(nn.Sequential):
    def __init__(self, in_c, out_c, kernel_size, stride=1, groups=1,
                 act="relu"):
        padding = (kernel_size - 1) // 2
        layers = [
            nn.Conv2D(in_c, out_c, kernel_size, stride=stride,
                      padding=padding, groups=groups, bias_attr=False),
            nn.BatchNorm2D(out_c),
        ]
        if act is not None:
            layers.append(_act(act))
        super().__init__(*layers)


class InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                ConvBNAct(branch_c, branch_c, 1, act=act),
                ConvBNAct(branch_c, branch_c, 3, stride=stride,
                          groups=branch_c, act=None),
                ConvBNAct(branch_c, branch_c, 1, act=act))
        else:
            self.branch1 = nn.Sequential(
                ConvBNAct(in_c, in_c, 3, stride=stride, groups=in_c,
                          act=None),
                ConvBNAct(in_c, branch_c, 1, act=act))
            self.branch2 = nn.Sequential(
                ConvBNAct(in_c, branch_c, 1, act=act),
                ConvBNAct(branch_c, branch_c, 3, stride=stride,
                          groups=branch_c, act=None),
                ConvBNAct(branch_c, branch_c, 1, act=act))
        self.shuffle = nn.ChannelShuffle(2)

    def forward(self, x):
        if self.stride == 1:
            x1, x2 = split(x, 2, axis=1)
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return self.shuffle(out)


_STAGE_CFG = {
    0.25: [24, 24, 48, 96, 512],
    0.33: [24, 32, 64, 128, 512],
    0.5: [24, 48, 96, 192, 1024],
    1.0: [24, 116, 232, 464, 1024],
    1.5: [24, 176, 352, 704, 1024],
    2.0: [24, 244, 488, 976, 2048],
}
_REPEATS = [4, 8, 4]


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        channels = _STAGE_CFG[scale]
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.conv1 = ConvBNAct(3, channels[0], 3, stride=2, act=act)
        self.max_pool = nn.MaxPool2D(3, stride=2, padding=1)

        blocks = []
        in_c = channels[0]
        for stage_i, rep in enumerate(_REPEATS):
            out_c = channels[stage_i + 1]
            for i in range(rep):
                blocks.append(InvertedResidual(in_c, out_c,
                                               stride=2 if i == 0 else 1,
                                               act=act))
                in_c = out_c
        self.blocks = nn.Sequential(*blocks)
        self.conv_last = ConvBNAct(in_c, channels[-1], 1, act=act)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(channels[-1], num_classes)

    def forward(self, x):
        x = self.max_pool(self.conv1(x))
        x = self.blocks(x)
        x = self.conv_last(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.25, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.33, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.5, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.5, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=2.0, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, act="swish", **kwargs)
