"""MobileNetV2 (analogue of python/paddle/vision/models/mobilenetv2.py)."""

from __future__ import annotations

from ... import nn

__all__ = ["MobileNetV2", "mobilenet_v2"]


def _make_divisible(v, divisor=8, min_value=None):
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBNReLU(nn.Sequential):
    def __init__(self, in_planes, out_planes, kernel_size=3, stride=1,
                 groups=1):
        padding = (kernel_size - 1) // 2
        super().__init__(
            nn.Conv2D(in_planes, out_planes, kernel_size, stride=stride,
                      padding=padding, groups=groups, bias_attr=False),
            nn.BatchNorm2D(out_planes),
            nn.ReLU6())


class InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        self.stride = stride
        hidden_dim = int(round(inp * expand_ratio))
        self.use_res_connect = stride == 1 and inp == oup

        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNReLU(inp, hidden_dim, kernel_size=1))
        layers.extend([
            ConvBNReLU(hidden_dim, hidden_dim, stride=stride,
                       groups=hidden_dim),
            nn.Conv2D(hidden_dim, oup, 1, bias_attr=False),
            nn.BatchNorm2D(oup),
        ])
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        if self.use_res_connect:
            return x + self.conv(x)
        return self.conv(x)


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        input_channel = 32
        last_channel = 1280
        # t (expand), c (channels), n (repeats), s (stride)
        inverted_residual_setting = [
            [1, 16, 1, 1], [6, 24, 2, 2], [6, 32, 3, 2], [6, 64, 4, 2],
            [6, 96, 3, 1], [6, 160, 3, 2], [6, 320, 1, 1],
        ]

        input_channel = _make_divisible(input_channel * scale)
        self.last_channel = _make_divisible(last_channel * max(1.0, scale))
        features = [ConvBNReLU(3, input_channel, stride=2)]
        for t, c, n, s in inverted_residual_setting:
            output_channel = _make_divisible(c * scale)
            for i in range(n):
                stride = s if i == 0 else 1
                features.append(InvertedResidual(input_channel, output_channel,
                                                 stride, expand_ratio=t))
                input_channel = output_channel
        features.append(ConvBNReLU(input_channel, self.last_channel,
                                   kernel_size=1))
        self.features = nn.Sequential(*features)
        if with_pool:
            self.pool2d_avg = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(self.last_channel, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool2d_avg(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)
