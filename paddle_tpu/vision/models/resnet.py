"""ResNet (analogue of python/paddle/vision/models/resnet.py).

NCHW is the default (reference API parity).  ``data_format="NHWC"``
runs the whole tower channels-last.  Measured on v5e (BASELINE.md
round-5 conv attribution): the two layouts are THROUGHPUT-NEUTRAL for
the b128 train step (52.20 vs 51.30 ms) — XLA's internal layout
assignment is already channels-minor either way, and the slow
56x56-stage 1x1 fusions are activation-HBM-bound, not layout-bound.
NHWC is kept because it is the natural layout for TPU-side data
pipelines (and other accelerators' channels-last checkpoints), not as
a performance fix.
"""

from __future__ import annotations

from ... import nn

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
           "resnet152", "wide_resnet50_2", "wide_resnet101_2"]


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, norm_layer=None,
                 data_format="NCHW"):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        df = dict(data_format=data_format)
        self.conv1 = nn.Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                               bias_attr=False, **df)
        self.bn1 = norm_layer(planes, **df)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False,
                               **df)
        self.bn2 = norm_layer(planes, **df)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, norm_layer=None,
                 data_format="NCHW"):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        df = dict(data_format=data_format)
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False, **df)
        self.bn1 = norm_layer(width, **df)
        self.conv2 = nn.Conv2D(width, width, 3, padding=dilation,
                               stride=stride, groups=groups,
                               dilation=dilation, bias_attr=False, **df)
        self.bn2 = norm_layer(width, **df)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1,
                               bias_attr=False, **df)
        self.bn3 = norm_layer(planes * self.expansion, **df)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    def __init__(self, block, depth=50, width=64, num_classes=1000,
                 with_pool=True, groups=1, data_format="NCHW",
                 input_format="NCHW"):
        super().__init__()
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        layers = layer_cfg[depth]
        self.groups = groups
        self.base_width = width
        self.num_classes = num_classes
        self.with_pool = with_pool
        self._norm_layer = nn.BatchNorm2D
        self._data_format = data_format
        self._input_format = input_format
        df = dict(data_format=data_format)
        self.inplanes = 64
        self.dilation = 1
        self.conv1 = nn.Conv2D(3, self.inplanes, 7, stride=2, padding=3,
                               bias_attr=False, **df)
        self.bn1 = self._norm_layer(self.inplanes, **df)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1, **df)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1), **df)
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1, dilate=False):
        norm_layer = self._norm_layer
        df = dict(data_format=self._data_format)
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False, **df),
                norm_layer(planes * block.expansion, **df))
        layers = [block(self.inplanes, planes, stride, downsample, self.groups,
                        self.base_width, self.dilation, norm_layer,
                        data_format=self._data_format)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, groups=self.groups,
                                base_width=self.base_width,
                                norm_layer=norm_layer,
                                data_format=self._data_format))
        return nn.Sequential(*layers)

    def forward(self, x):
        if self._data_format != self._input_format:
            # the input layout is DECLARED (input_format), never guessed
            # from shapes — a [N, 3, H, 3] batch would be ambiguous.
            # One entry transpose of the 3-channel input is tiny.
            x = (x.transpose([0, 2, 3, 1])
                 if self._data_format == "NHWC"
                 else x.transpose([0, 3, 1, 2]))
        x = self.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


def _resnet(block, depth, width=64, pretrained=False, **kwargs):
    return ResNet(block, depth, width=width, **kwargs)


def resnet18(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 18, pretrained=pretrained, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 34, pretrained=pretrained, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 50, pretrained=pretrained, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 101, pretrained=pretrained, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 152, pretrained=pretrained, **kwargs)


def wide_resnet50_2(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 50, width=128, pretrained=pretrained,
                   **kwargs)


def wide_resnet101_2(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 101, width=128, pretrained=pretrained,
                   **kwargs)
