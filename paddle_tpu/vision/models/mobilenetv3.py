"""MobileNetV3 (analogue of python/paddle/vision/models/mobilenetv3.py)."""

from __future__ import annotations

from ... import nn
from .mobilenetv2 import _make_divisible

__all__ = ["MobileNetV3Small", "MobileNetV3Large", "mobilenet_v3_small",
           "mobilenet_v3_large"]

_ACTS = {"relu": nn.ReLU, "hardswish": nn.Hardswish}


class ConvNormActivation(nn.Sequential):
    def __init__(self, in_channels, out_channels, kernel_size=3, stride=1,
                 groups=1, activation="relu"):
        padding = (kernel_size - 1) // 2
        layers = [
            nn.Conv2D(in_channels, out_channels, kernel_size, stride=stride,
                      padding=padding, groups=groups, bias_attr=False),
            nn.BatchNorm2D(out_channels),
        ]
        if activation is not None:
            layers.append(_ACTS[activation]())
        super().__init__(*layers)


class SqueezeExcitation(nn.Layer):
    def __init__(self, input_channels, squeeze_channels):
        super().__init__()
        self.avgpool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(input_channels, squeeze_channels, 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(squeeze_channels, input_channels, 1)
        self.hardsigmoid = nn.Hardsigmoid()

    def forward(self, x):
        scale = self.avgpool(x)
        scale = self.relu(self.fc1(scale))
        scale = self.hardsigmoid(self.fc2(scale))
        return x * scale


class InvertedResidual(nn.Layer):
    def __init__(self, in_channels, expanded_channels, out_channels,
                 kernel_size, stride, use_se, activation):
        super().__init__()
        self.use_res_connect = stride == 1 and in_channels == out_channels
        layers = []
        if expanded_channels != in_channels:
            layers.append(ConvNormActivation(in_channels, expanded_channels,
                                             kernel_size=1,
                                             activation=activation))
        layers.append(ConvNormActivation(expanded_channels, expanded_channels,
                                         kernel_size=kernel_size,
                                         stride=stride,
                                         groups=expanded_channels,
                                         activation=activation))
        if use_se:
            layers.append(SqueezeExcitation(
                expanded_channels, _make_divisible(expanded_channels // 4)))
        layers.append(ConvNormActivation(expanded_channels, out_channels,
                                         kernel_size=1, activation=None))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        if self.use_res_connect:
            out = out + x
        return out


class MobileNetV3(nn.Layer):
    def __init__(self, config, last_channel, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        firstconv_out = _make_divisible(16 * scale)
        layers = [ConvNormActivation(3, firstconv_out, kernel_size=3, stride=2,
                                     activation="hardswish")]
        in_c = firstconv_out
        for k, exp, c, use_se, act, s in config:
            exp_c = _make_divisible(exp * scale)
            out_c = _make_divisible(c * scale)
            layers.append(InvertedResidual(in_c, exp_c, out_c, k, s, use_se,
                                           act))
            in_c = out_c
        lastconv_out = 6 * in_c
        layers.append(ConvNormActivation(in_c, lastconv_out, kernel_size=1,
                                         activation="hardswish"))
        self.features = nn.Sequential(*layers)
        self.lastconv_out = lastconv_out
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(lastconv_out, last_channel),
                nn.Hardswish(),
                nn.Dropout(0.2),
                nn.Linear(last_channel, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


# (kernel, expanded, out, use_se, activation, stride)
_SMALL = [
    (3, 16, 16, True, "relu", 2),
    (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1),
    (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1),
    (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1),
    (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2),
    (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]

_LARGE = [
    (3, 16, 16, False, "relu", 1),
    (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1),
    (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1),
    (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2),
    (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1),
    (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2),
    (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]


class MobileNetV3Small(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL, last_channel=1024, scale=scale,
                         num_classes=num_classes, with_pool=with_pool)


class MobileNetV3Large(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE, last_channel=1280, scale=scale,
                         num_classes=num_classes, with_pool=with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Large(scale=scale, **kwargs)
