"""Vision datasets (reference ``python/paddle/vision/datasets/cifar.py:41``,
``mnist.py``).

Two modes:

- ``data_file``/``image_path`` given: parse the REAL archive formats —
  CIFAR's pickled-batch tar.gz, MNIST's idx-ubyte gzip — exactly like the
  reference parsers (``cifar.py _load_data``, ``mnist.py
  _parse_dataset``).
- no path (default): deterministic synthetic data with the real
  shapes/label spaces.  This environment has zero egress, so
  ``download=True`` raises with a pointer to the file-path mode rather
  than pretending to fetch.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ...io import Dataset

__all__ = ["Cifar10", "Cifar100", "MNIST", "FashionMNIST"]


def _no_download(name):
    raise RuntimeError(
        f"{name}: download=True is unavailable in this environment "
        "(zero egress). Pass data_file=/path/to/archive (CIFAR: the "
        "cifar-*-python.tar.gz; MNIST: image_path/label_path idx-ubyte "
        ".gz files) or use the synthetic default (no path).")


class _SyntheticImages(Dataset):
    num_classes = 10
    image_shape = (3, 32, 32)

    def __init__(self, mode="train", transform=None, size=None, seed=0):
        self.mode = mode
        self.transform = transform
        self.size = size or (1024 if mode == "train" else 256)
        rng = np.random.default_rng(seed + (0 if mode == "train" else 1))
        c, h, w = self.image_shape
        # HWC uint8 like the real decoded datasets
        self.images = rng.integers(0, 256, (self.size, h, w, c),
                                   dtype=np.uint8)
        self.labels = rng.integers(0, self.num_classes, (self.size,),
                                   dtype=np.int64)

    def _finish_init(self):
        self.size = len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = (img.astype(np.float32) / 255.0).transpose(2, 0, 1)
        return img, self.labels[idx]

    def __len__(self):
        return self.size


class _Cifar(_SyntheticImages):
    _label_key = b"labels"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None, size=None, seed=0):
        if data_file:
            self.mode = mode
            self.transform = transform
            self.images, self.labels = self._parse(data_file, mode)
            self._finish_init()
        elif download:
            _no_download(type(self).__name__)
        else:
            super().__init__(mode=mode, transform=transform, size=size,
                             seed=seed)

    def _members(self, mode):
        raise NotImplementedError

    def _parse(self, data_file, mode):
        """Reference cifar.py: each tar member is a pickled dict with
        b'data' ([N, 3072] uint8, CHW-flattened) and the label list."""
        wanted = self._members(mode)
        images, labels = [], []
        with tarfile.open(data_file, "r:*") as tf:
            names = {os.path.basename(m.name): m for m in tf.getmembers()
                     if m.isfile()}
            for base in wanted:
                if base not in names:
                    continue
                with tf.extractfile(names[base]) as f:
                    batch = pickle.load(f, encoding="bytes")
                data = np.asarray(batch[b"data"], np.uint8)
                images.append(data.reshape(-1, 3, 32, 32)
                              .transpose(0, 2, 3, 1))  # -> HWC
                labels.append(np.asarray(batch[self._label_key], np.int64))
        if not images:
            raise ValueError(
                f"{type(self).__name__}: no '{mode}' batches found in "
                f"{data_file} (expected members like {wanted[0]})")
        return np.concatenate(images), np.concatenate(labels)


class Cifar10(_Cifar):
    num_classes = 10
    image_shape = (3, 32, 32)
    _label_key = b"labels"

    def _members(self, mode):
        if mode == "train":
            return [f"data_batch_{i}" for i in range(1, 6)]
        return ["test_batch"]


class Cifar100(_Cifar):
    num_classes = 100
    image_shape = (3, 32, 32)
    _label_key = b"fine_labels"

    def _members(self, mode):
        return ["train"] if mode == "train" else ["test"]


def _open_maybe_gz(path):
    with open(path, "rb") as probe:
        magic = probe.read(2)
    return gzip.open(path, "rb") if magic == b"\x1f\x8b" else \
        open(path, "rb")


class MNIST(_SyntheticImages):
    num_classes = 10
    image_shape = (1, 28, 28)

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None, size=None,
                 seed=0):
        if bool(image_path) != bool(label_path):
            raise ValueError(
                "MNIST: image_path and label_path must be given together "
                "(got only one) — a silent synthetic fallback would look "
                "like real data")
        if image_path and label_path:
            self.mode = mode
            self.transform = transform
            self.images = self._parse_images(image_path)
            self.labels = self._parse_labels(label_path)
            if len(self.images) != len(self.labels):
                raise ValueError(
                    f"MNIST: {len(self.images)} images vs "
                    f"{len(self.labels)} labels")
            self._finish_init()
        elif download and not (image_path or label_path):
            _no_download(type(self).__name__)
        else:
            super().__init__(mode=mode, transform=transform, size=size,
                             seed=seed)

    @staticmethod
    def _parse_images(path):
        """idx3-ubyte: >u4 magic 2051 | count | rows | cols | pixels."""
        with _open_maybe_gz(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            if magic != 2051:
                raise ValueError(
                    f"MNIST image file {path}: bad magic {magic} "
                    "(want 2051)")
            buf = f.read(n * rows * cols)
        return np.frombuffer(buf, np.uint8).reshape(n, rows, cols, 1)

    @staticmethod
    def _parse_labels(path):
        """idx1-ubyte: >u4 magic 2049 | count | labels."""
        with _open_maybe_gz(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            if magic != 2049:
                raise ValueError(
                    f"MNIST label file {path}: bad magic {magic} "
                    "(want 2049)")
            buf = f.read(n)
        return np.frombuffer(buf, np.uint8).astype(np.int64)


class FashionMNIST(MNIST):
    pass
