"""Vision datasets (reference ``python/paddle/vision/datasets/cifar.py:41``,
``mnist.py``).

Two modes:

- ``data_file``/``image_path`` given: parse the REAL archive formats —
  CIFAR's pickled-batch tar.gz, MNIST's idx-ubyte gzip — exactly like the
  reference parsers (``cifar.py _load_data``, ``mnist.py
  _parse_dataset``).
- no path (default): deterministic synthetic data with the real
  shapes/label spaces.  This environment has zero egress, so
  ``download=True`` raises with a pointer to the file-path mode rather
  than pretending to fetch.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ...io import Dataset

__all__ = ["Cifar10", "Cifar100", "MNIST", "FashionMNIST",
           "VOC2012", "Flowers"]


def _no_download(name):
    raise RuntimeError(
        f"{name}: download=True is unavailable in this environment "
        "(zero egress). Pass data_file=/path/to/archive (CIFAR: the "
        "cifar-*-python.tar.gz; MNIST: image_path/label_path idx-ubyte "
        ".gz files) or use the synthetic default (no path).")


class _SyntheticImages(Dataset):
    num_classes = 10
    image_shape = (3, 32, 32)

    def __init__(self, mode="train", transform=None, size=None, seed=0):
        self.mode = mode
        self.transform = transform
        self.size = size or (1024 if mode == "train" else 256)
        rng = np.random.default_rng(seed + (0 if mode == "train" else 1))
        c, h, w = self.image_shape
        # HWC uint8 like the real decoded datasets
        self.images = rng.integers(0, 256, (self.size, h, w, c),
                                   dtype=np.uint8)
        self.labels = rng.integers(0, self.num_classes, (self.size,),
                                   dtype=np.int64)

    def _finish_init(self):
        self.size = len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = (img.astype(np.float32) / 255.0).transpose(2, 0, 1)
        return img, self.labels[idx]

    def __len__(self):
        return self.size


class _Cifar(_SyntheticImages):
    _label_key = b"labels"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None, size=None, seed=0):
        if data_file:
            self.mode = mode
            self.transform = transform
            self.images, self.labels = self._parse(data_file, mode)
            self._finish_init()
        elif download:
            _no_download(type(self).__name__)
        else:
            super().__init__(mode=mode, transform=transform, size=size,
                             seed=seed)

    def _members(self, mode):
        raise NotImplementedError

    def _parse(self, data_file, mode):
        """Reference cifar.py: each tar member is a pickled dict with
        b'data' ([N, 3072] uint8, CHW-flattened) and the label list."""
        wanted = self._members(mode)
        images, labels = [], []
        with tarfile.open(data_file, "r:*") as tf:
            names = {os.path.basename(m.name): m for m in tf.getmembers()
                     if m.isfile()}
            for base in wanted:
                if base not in names:
                    continue
                with tf.extractfile(names[base]) as f:
                    batch = pickle.load(f, encoding="bytes")
                data = np.asarray(batch[b"data"], np.uint8)
                images.append(data.reshape(-1, 3, 32, 32)
                              .transpose(0, 2, 3, 1))  # -> HWC
                labels.append(np.asarray(batch[self._label_key], np.int64))
        if not images:
            raise ValueError(
                f"{type(self).__name__}: no '{mode}' batches found in "
                f"{data_file} (expected members like {wanted[0]})")
        return np.concatenate(images), np.concatenate(labels)


class Cifar10(_Cifar):
    num_classes = 10
    image_shape = (3, 32, 32)
    _label_key = b"labels"

    def _members(self, mode):
        if mode == "train":
            return [f"data_batch_{i}" for i in range(1, 6)]
        return ["test_batch"]


class Cifar100(_Cifar):
    num_classes = 100
    image_shape = (3, 32, 32)
    _label_key = b"fine_labels"

    def _members(self, mode):
        return ["train"] if mode == "train" else ["test"]


def _open_maybe_gz(path):
    with open(path, "rb") as probe:
        magic = probe.read(2)
    return gzip.open(path, "rb") if magic == b"\x1f\x8b" else \
        open(path, "rb")


class MNIST(_SyntheticImages):
    num_classes = 10
    image_shape = (1, 28, 28)

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None, size=None,
                 seed=0):
        if bool(image_path) != bool(label_path):
            raise ValueError(
                "MNIST: image_path and label_path must be given together "
                "(got only one) — a silent synthetic fallback would look "
                "like real data")
        if image_path and label_path:
            self.mode = mode
            self.transform = transform
            self.images = self._parse_images(image_path)
            self.labels = self._parse_labels(label_path)
            if len(self.images) != len(self.labels):
                raise ValueError(
                    f"MNIST: {len(self.images)} images vs "
                    f"{len(self.labels)} labels")
            self._finish_init()
        elif download and not (image_path or label_path):
            _no_download(type(self).__name__)
        else:
            super().__init__(mode=mode, transform=transform, size=size,
                             seed=seed)

    @staticmethod
    def _parse_images(path):
        """idx3-ubyte: >u4 magic 2051 | count | rows | cols | pixels."""
        with _open_maybe_gz(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            if magic != 2051:
                raise ValueError(
                    f"MNIST image file {path}: bad magic {magic} "
                    "(want 2051)")
            buf = f.read(n * rows * cols)
        return np.frombuffer(buf, np.uint8).reshape(n, rows, cols, 1)

    @staticmethod
    def _parse_labels(path):
        """idx1-ubyte: >u4 magic 2049 | count | labels."""
        with _open_maybe_gz(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            if magic != 2049:
                raise ValueError(
                    f"MNIST label file {path}: bad magic {magic} "
                    "(want 2049)")
            buf = f.read(n)
        return np.frombuffer(buf, np.uint8).astype(np.int64)


class FashionMNIST(MNIST):
    pass


class VOC2012(Dataset):
    """Segmentation pairs (reference
    ``python/paddle/vision/datasets/voc2012.py``): items are
    ``(image, label)`` — RGB image and the class-index mask png (0..20,
    255 = void border), both HWC/HW uint8 before transforms.

    ``data_file``: the real VOCtrainval tar (ImageSets/Segmentation/
    {mode}.txt lists the ids; JPEGImages/<id>.jpg +
    SegmentationClass/<id>.png).  Without a path: synthetic image/mask
    pairs with the 21-class label space."""

    num_classes = 21

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None, size=None, seed=0):
        if mode not in ("train", "valid", "test"):
            raise ValueError(
                f"mode should be 'train', 'valid' or 'test', got {mode}")
        self.mode = mode
        self.transform = transform
        if data_file:
            self._open(data_file, mode)
            return
        if download:
            _no_download(type(self).__name__)
        self._tar = None
        self.size = (128 if mode == "train" else 32) if size is None \
            else size
        rng = np.random.default_rng(
            seed + {"train": 0, "valid": 1, "test": 2}[mode])
        self._images = rng.integers(0, 256, (self.size, 64, 64, 3),
                                    dtype=np.uint8)
        masks = rng.integers(0, self.num_classes, (self.size, 64, 64))
        masks[:, :2, :] = 255  # void border rows like real masks
        self._masks = masks.astype(np.uint8)

    def _open(self, data_file, mode):
        import tarfile
        # reference MODE_FLAG_MAP (voc2012.py:36): 'train' reads the
        # trainval superset, 'test' reads train.txt, 'valid' reads val
        split = {"train": "trainval", "valid": "val",
                 "test": "train"}[mode]
        self._tar = tarfile.open(data_file, "r:*")
        members = {m.name: m for m in self._tar.getmembers()
                   if m.isfile()}
        list_name = [n for n in members if n.endswith(
            f"ImageSets/Segmentation/{split}.txt")]
        if len(list_name) != 1:
            raise ValueError(
                f"VOC2012: no ImageSets/Segmentation/{split}.txt in "
                f"{data_file}")
        ids = self._tar.extractfile(members[list_name[0]]) \
            .read().decode().split()
        root = list_name[0].split("ImageSets/")[0]
        self._pairs = []
        for i in ids:
            jpg = f"{root}JPEGImages/{i}.jpg"
            png = f"{root}SegmentationClass/{i}.png"
            if jpg in members and png in members:
                self._pairs.append((members[jpg], members[png]))
        self.size = len(self._pairs)

    def __getitem__(self, idx):
        if self._tar is not None:
            import io
            from PIL import Image
            jm, pm = self._pairs[idx]
            img = np.asarray(Image.open(io.BytesIO(
                self._tar.extractfile(jm).read())).convert("RGB"))
            mask = np.asarray(Image.open(io.BytesIO(
                self._tar.extractfile(pm).read())))
        else:
            img, mask = self._images[idx], self._masks[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, mask

    def __len__(self):
        return self.size


class Flowers(Dataset):
    """102-category flowers (reference
    ``python/paddle/vision/datasets/flowers.py``): items are
    ``(image, label)`` with the 1-based class id in an int64 [1] array.

    Real files: ``data_file`` = 102flowers.tgz (jpg/image_NNNNN.jpg),
    ``label_file`` = imagelabels.mat, ``setid_file`` = setid.mat
    (trnid/valid/tstid index lists).  Without paths: synthetic images
    over the real label space."""

    num_classes = 102
    # reference flowers.py:38 deliberately swaps trnid/tstid (the
    # dataset's test split outnumbers train ~6x, so 'train' uses tstid)
    _split_key = {"train": "tstid", "valid": "valid", "test": "trnid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=False,
                 backend=None, size=None, seed=0):
        if mode not in self._split_key:
            raise ValueError(
                f"mode should be 'train', 'valid' or 'test', got {mode}")
        self.mode = mode
        self.transform = transform
        if data_file:
            if not (label_file and setid_file):
                raise ValueError("Flowers needs data_file + label_file + "
                                 "setid_file together")
            self._open(data_file, label_file, setid_file, mode)
            return
        if download:
            _no_download(type(self).__name__)
        self._tar = None
        self.size = (256 if mode == "train" else 64) if size is None \
            else size
        rng = np.random.default_rng(
            seed + {"train": 0, "valid": 1, "test": 2}[mode])
        self._images = rng.integers(0, 256, (self.size, 64, 64, 3),
                                    dtype=np.uint8)
        self.labels = rng.integers(1, self.num_classes + 1,
                                   (self.size,)).astype(np.int64)

    def _open(self, data_file, label_file, setid_file, mode):
        import tarfile
        import scipy.io
        self._tar = tarfile.open(data_file, "r:*")
        self._members = {m.name: m for m in self._tar.getmembers()
                         if m.isfile()}
        self.labels = np.asarray(
            scipy.io.loadmat(label_file)["labels"]).ravel() \
            .astype(np.int64)
        self.indexes = np.asarray(scipy.io.loadmat(setid_file)[
            self._split_key[mode]]).ravel().astype(np.int64)
        self.size = len(self.indexes)

    def __getitem__(self, idx):
        if self._tar is not None:
            import io
            from PIL import Image
            index = int(self.indexes[idx])
            name = "jpg/image_%05d.jpg" % index
            img = np.asarray(Image.open(io.BytesIO(
                self._tar.extractfile(self._members[name]).read()))
                .convert("RGB"))
            label = np.asarray([self.labels[index - 1]], np.int64)
        else:
            img = self._images[idx]
            label = np.asarray([self.labels[idx]], np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return self.size
