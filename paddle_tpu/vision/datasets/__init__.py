"""Vision datasets.  Zero-egress environment: synthetic datasets with the
reference datasets' shapes/APIs (Cifar10/MNIST signatures), generated
deterministically — the data pipeline and training loops exercise the same
code paths as the real downloads."""

from __future__ import annotations

import numpy as np

from ...io import Dataset

__all__ = ["Cifar10", "Cifar100", "MNIST", "FashionMNIST"]


class _SyntheticImages(Dataset):
    num_classes = 10
    image_shape = (3, 32, 32)

    def __init__(self, mode="train", transform=None, size=None, seed=0):
        self.mode = mode
        self.transform = transform
        self.size = size or (1024 if mode == "train" else 256)
        rng = np.random.default_rng(seed + (0 if mode == "train" else 1))
        c, h, w = self.image_shape
        # HWC uint8 like the real decoded datasets
        self.images = rng.integers(0, 256, (self.size, h, w, c),
                                   dtype=np.uint8)
        self.labels = rng.integers(0, self.num_classes, (self.size,),
                                   dtype=np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = (img.astype(np.float32) / 255.0).transpose(2, 0, 1)
        return img, self.labels[idx]

    def __len__(self):
        return self.size


class Cifar10(_SyntheticImages):
    num_classes = 10
    image_shape = (3, 32, 32)


class Cifar100(_SyntheticImages):
    num_classes = 100
    image_shape = (3, 32, 32)


class MNIST(_SyntheticImages):
    num_classes = 10
    image_shape = (1, 28, 28)


class FashionMNIST(MNIST):
    pass
