"""Minimal transforms (analogue of python/paddle/vision/transforms/)."""

from __future__ import annotations

import numpy as np

__all__ = ["Compose", "Normalize", "ToTensor", "Resize", "Transpose",
           "RandomHorizontalFlip", "RandomCrop"]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img, dtype=np.float32)
        if self.data_format == "CHW":
            mean = self.mean.reshape(-1, 1, 1)
            std = self.std.reshape(-1, 1, 1)
        else:
            mean = self.mean
            std = self.std
        return (img - mean) / std


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, dtype=np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[None] if self.data_format == "CHW" else arr[..., None]
        elif self.data_format == "CHW" and arr.shape[-1] in (1, 3, 4):
            arr = arr.transpose(2, 0, 1)
        return arr


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = size if isinstance(size, (tuple, list)) else (size, size)

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = self.size
        # nearest resize (dependency-free)
        ih, iw = arr.shape[0], arr.shape[1]
        rows = (np.arange(h) * ih // h)
        cols = (np.arange(w) * iw // w)
        return arr[rows][:, cols]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return img


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = size if isinstance(size, (tuple, list)) else (size, size)
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        if self.padding:
            pad = [(self.padding, self.padding), (self.padding, self.padding)]
            if arr.ndim == 3:
                pad.append((0, 0))
            arr = np.pad(arr, pad)
        h, w = self.size
        top = np.random.randint(0, arr.shape[0] - h + 1)
        left = np.random.randint(0, arr.shape[1] - w + 1)
        return arr[top:top + h, left:left + w]


class CenterCrop:
    """Crop the central region (reference transforms.CenterCrop)."""

    def __init__(self, size):
        self.size = size if isinstance(size, (tuple, list)) else (size, size)

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = self.size
        if h > arr.shape[0] or w > arr.shape[1]:
            raise ValueError(
                f"CenterCrop size {self.size} exceeds image shape "
                f"{arr.shape[:2]}")
        top = (arr.shape[0] - h) // 2
        left = (arr.shape[1] - w) // 2
        return arr[top:top + h, left:left + w]


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[::-1].copy()
        return img


class Pad:
    """Pad HWC/HW images on all (or per-side) borders."""

    def __init__(self, padding, fill=0, padding_mode="constant"):
        if isinstance(padding, int):
            padding = (padding, padding, padding, padding)  # l, t, r, b
        elif len(padding) == 2:
            padding = (padding[0], padding[1], padding[0], padding[1])
        self.padding = padding
        self.fill = fill
        self.mode = padding_mode

    def __call__(self, img):
        arr = np.asarray(img)
        l, t, r, b = self.padding
        pad2d = [(t, b), (l, r)]
        if self.mode != "constant":
            pad = pad2d + ([(0, 0)] if arr.ndim == 3 else [])
            return np.pad(arr, pad, mode=self.mode)
        if isinstance(self.fill, (tuple, list)) and arr.ndim == 3:
            # per-channel fill color (reference accepts RGB tuples)
            return np.stack(
                [np.pad(arr[..., c], pad2d, constant_values=self.fill[c])
                 for c in range(arr.shape[-1])], axis=-1)
        pad = pad2d + ([(0, 0)] if arr.ndim == 3 else [])
        return np.pad(arr, pad, constant_values=self.fill)


class Grayscale:
    """HWC RGB -> grayscale with the ITU-R 601 luma weights."""

    def __init__(self, num_output_channels=1):
        self.num_output_channels = num_output_channels

    def __call__(self, img):
        arr = np.asarray(img, dtype=np.float32)
        if arr.ndim == 2:
            gray = arr
        else:
            gray = (0.299 * arr[..., 0] + 0.587 * arr[..., 1]
                    + 0.114 * arr[..., 2])
        gray = gray.astype(np.asarray(img).dtype)
        if self.num_output_channels == 3:
            return np.stack([gray] * 3, axis=-1)
        return gray[..., None]


def _jitter_range(value):
    """Reference semantics: scalar v -> [max(0, 1-v), 1+v]; (lo, hi) tuple
    passes through.  Returns None when the jitter is a no-op."""
    if isinstance(value, (tuple, list)):
        lo, hi = float(value[0]), float(value[1])
        if lo < 0 or lo > hi:
            raise ValueError(
                f"jitter range must satisfy 0 <= lo <= hi, got ({lo}, {hi})")
        if lo == hi == 1.0:
            return None
        return (lo, hi)
    if value == 0:
        return None
    return (max(0.0, 1.0 - value), 1.0 + value)


class BrightnessTransform:
    def __init__(self, value):
        self.range = _jitter_range(value)

    def __call__(self, img):
        if self.range is None:
            return img
        arr = np.asarray(img).astype(np.float32)
        alpha = np.random.uniform(*self.range)
        return np.clip(arr * alpha, 0, 255).astype(np.asarray(img).dtype)


class ContrastTransform:
    def __init__(self, value):
        self.range = _jitter_range(value)

    def __call__(self, img):
        if self.range is None:
            return img
        arr = np.asarray(img).astype(np.float32)
        alpha = np.random.uniform(*self.range)
        mean = arr.mean()
        return np.clip(mean + alpha * (arr - mean), 0, 255) \
            .astype(np.asarray(img).dtype)


class SaturationTransform:
    """Blend with the grayscale image (standard saturation jitter)."""

    def __init__(self, value):
        self.range = _jitter_range(value)

    def __call__(self, img):
        if self.range is None:
            return img
        arr = np.asarray(img).astype(np.float32)
        alpha = np.random.uniform(*self.range)
        gray = (0.299 * arr[..., 0] + 0.587 * arr[..., 1]
                + 0.114 * arr[..., 2])[..., None]
        return np.clip(gray + alpha * (arr - gray), 0, 255) \
            .astype(np.asarray(img).dtype)


class HueTransform:
    """Shift hue in HSV space (value in [0, 0.5], reference range)."""

    def __init__(self, value):
        if isinstance(value, (tuple, list)):
            lo, hi = float(value[0]), float(value[1])
            if not -0.5 <= lo <= hi <= 0.5:
                raise ValueError("hue range must lie in [-0.5, 0.5]")
            self.range = None if lo == hi == 0.0 else (lo, hi)
        else:
            if not 0 <= value <= 0.5:
                raise ValueError("hue value must be in [0, 0.5]")
            self.range = None if value == 0 else (-value, value)

    def __call__(self, img):
        if self.range is None:
            return img
        arr = np.asarray(img).astype(np.float32) / 255.0
        r, g, b = arr[..., 0], arr[..., 1], arr[..., 2]
        maxc = arr.max(-1)
        minc = arr.min(-1)
        v = maxc
        span = np.where(maxc > 0, maxc - minc, 0.0)
        s_ = np.where(maxc > 0, span / np.maximum(maxc, 1e-12), 0.0)
        safe = np.maximum(span, 1e-12)
        rc = (maxc - r) / safe
        gc = (maxc - g) / safe
        bc = (maxc - b) / safe
        h = np.where(r == maxc, bc - gc,
                     np.where(g == maxc, 2.0 + rc - bc, 4.0 + gc - rc))
        h = (h / 6.0) % 1.0
        h = (h + np.random.uniform(*self.range)) % 1.0
        i = (h * 6.0).astype(np.int32) % 6
        f = h * 6.0 - np.floor(h * 6.0)
        p_ = v * (1.0 - s_)
        q_ = v * (1.0 - s_ * f)
        t_ = v * (1.0 - s_ * (1.0 - f))
        choices = [(v, t_, p_), (q_, v, p_), (p_, v, t_),
                   (p_, q_, v), (t_, p_, v), (v, p_, q_)]
        out = np.zeros_like(arr)
        for idx, (rr, gg, bb) in enumerate(choices):
            m = i == idx
            out[..., 0][m] = rr[m]
            out[..., 1][m] = gg[m]
            out[..., 2][m] = bb[m]
        return np.clip(out * 255.0, 0, 255).astype(np.asarray(img).dtype)


class ColorJitter:
    """Brightness/contrast/saturation/hue jitter (reference ColorJitter;
    saturation blends with luma, hue shifts in HSV)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.transforms = [BrightnessTransform(brightness),
                           ContrastTransform(contrast),
                           SaturationTransform(saturation),
                           HueTransform(hue)]

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class RandomResizedCrop:
    """Random area/aspect crop then resize (reference semantics,
    nearest-neighbor resize)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
        self.size = size if isinstance(size, (tuple, list)) else (size, size)
        self.scale = scale
        self.ratio = ratio

    def __call__(self, img):
        arr = np.asarray(img)
        ih, iw = arr.shape[0], arr.shape[1]
        area = ih * iw
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            aspect = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                              np.log(self.ratio[1])))
            w = int(round(np.sqrt(target * aspect)))
            h = int(round(np.sqrt(target / aspect)))
            if 0 < w <= iw and 0 < h <= ih:
                top = np.random.randint(0, ih - h + 1)
                left = np.random.randint(0, iw - w + 1)
                crop = arr[top:top + h, left:left + w]
                return Resize(self.size)(crop)
        return Resize(self.size)(CenterCrop((min(ih, iw),) * 2)(arr))


__all__ += ["CenterCrop", "RandomVerticalFlip", "Pad", "Grayscale",
            "BrightnessTransform", "ContrastTransform",
            "SaturationTransform", "HueTransform", "ColorJitter",
            "RandomResizedCrop"]
