"""Minimal transforms (analogue of python/paddle/vision/transforms/)."""

from __future__ import annotations

import numpy as np

__all__ = ["Compose", "Normalize", "ToTensor", "Resize", "Transpose",
           "RandomHorizontalFlip", "RandomCrop"]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img, dtype=np.float32)
        if self.data_format == "CHW":
            mean = self.mean.reshape(-1, 1, 1)
            std = self.std.reshape(-1, 1, 1)
        else:
            mean = self.mean
            std = self.std
        return (img - mean) / std


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, dtype=np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[None] if self.data_format == "CHW" else arr[..., None]
        elif self.data_format == "CHW" and arr.shape[-1] in (1, 3, 4):
            arr = arr.transpose(2, 0, 1)
        return arr


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = size if isinstance(size, (tuple, list)) else (size, size)

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = self.size
        # nearest resize (dependency-free)
        ih, iw = arr.shape[0], arr.shape[1]
        rows = (np.arange(h) * ih // h)
        cols = (np.arange(w) * iw // w)
        return arr[rows][:, cols]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return img


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = size if isinstance(size, (tuple, list)) else (size, size)
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        if self.padding:
            pad = [(self.padding, self.padding), (self.padding, self.padding)]
            if arr.ndim == 3:
                pad.append((0, 0))
            arr = np.pad(arr, pad)
        h, w = self.size
        top = np.random.randint(0, arr.shape[0] - h + 1)
        left = np.random.randint(0, arr.shape[1] - w + 1)
        return arr[top:top + h, left:left + w]
