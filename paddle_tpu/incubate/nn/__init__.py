"""Incubate nn — fused LLM blocks (analogue of python/paddle/incubate/nn/)."""

from . import functional  # noqa: F401
from .layer import FusedMultiHeadAttention, FusedFeedForward  # noqa: F401
