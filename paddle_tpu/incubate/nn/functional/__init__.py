"""Fused LLM functionals (analogue of python/paddle/incubate/nn/functional/:
fused_rms_norm, fused_rotary_position_embedding, fused_linear,
masked_multihead_attention, memory_efficient_attention).

On TPU "fused" means: one dispatch whose impl XLA/Pallas fuses — the API
names are kept for recipe compatibility.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ....core.dispatch import dispatch
from ....nn.functional.attention import scaled_dot_product_attention
from ....nn.functional.norm import layer_norm, rms_norm

__all__ = ["fused_rms_norm", "fused_layer_norm", "fused_linear",
           "fused_rotary_position_embedding", "rotary_position_embedding",
           "llama_rope", "fused_dropout_add", "masked_multihead_attention",
           "memory_efficient_attention", "fused_bias_act",
           "swiglu", "fused_linear_cross_entropy"]


def fused_rms_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, **kwargs):
    out = rms_norm(x, norm_weight, epsilon)
    return (out,) if kwargs.get("return_tuple") else out


def fused_layer_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-5,
                     begin_norm_axis=-1, **kwargs):
    shape = tuple(x.shape[begin_norm_axis:]) if begin_norm_axis != -1 \
        else (x.shape[-1],)
    return layer_norm(x, shape, norm_weight, norm_bias, epsilon)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    def impl(a, w, *rest):
        wt = w.T if transpose_weight else w
        out = jnp.matmul(a, wt)
        if rest:
            out = out + rest[0]
        return out

    args = (x, weight) + ((bias,) if bias is not None else ())
    return dispatch("fused_linear", impl, args)


def swiglu(x, y=None, name=None):
    """SwiGLU activation (reference incubate fused op used by Llama FFN)."""
    if y is not None:
        return dispatch("swiglu",
                        lambda a, b: jax.nn.silu(a) * b, (x, y))

    def impl(a):
        a1, a2 = jnp.split(a, 2, axis=-1)
        return jax.nn.silu(a1) * a2

    return dispatch("swiglu", impl, (x,))


def _rotate_half(v):
    v1, v2 = jnp.split(v, 2, axis=-1)
    return jnp.concatenate([-v2, v1], axis=-1)


def _rotate_every_two(v):
    """NeoX adjacent-pair rotation helper: rot[2i] = -v[2i+1],
    rot[2i+1] = v[2i]."""
    v_even = v[..., 0::2]
    v_odd = v[..., 1::2]
    return jnp.stack([-v_odd, v_even], axis=-1).reshape(v.shape)


def llama_rope(q, k, rotary_emb_base=10000.0, position_ids=None):
    """HF-Llama rotate_half RoPE with concat(freqs, freqs) tables — the hot
    path used by the Llama/GPT models.  Half-table form feeds the Pallas
    kernel directly (ops/pallas/rope.py).  q/k: [B, S, H, D]."""
    from ....ops.pallas import rope as pallas_rope
    d = q.shape[-1]
    s = q.shape[1]
    inv_freq = 1.0 / (rotary_emb_base ** (jnp.arange(0, d, 2,
                                                     dtype=jnp.float32) / d))
    if position_ids is not None:
        pos = position_ids._value if hasattr(position_ids, "_value") \
            else jnp.asarray(position_ids)
        freqs = pos[..., None].astype(jnp.float32) * inv_freq  # [B,S,d/2]
        cos_h = jnp.cos(freqs)[:, :, None, :]
        sin_h = jnp.sin(freqs)[:, :, None, :]
    else:
        t = jnp.arange(s, dtype=jnp.float32)
        freqs = jnp.outer(t, inv_freq)
        cos_h = jnp.cos(freqs)[None, :, None, :]
        sin_h = jnp.sin(freqs)[None, :, None, :]

    def rotate_one(xa):
        if position_ids is None and pallas_rope.should_use_pallas(xa):
            return pallas_rope.apply_rope(xa, cos_h, sin_h)
        xf = xa.astype(jnp.float32)
        cos2 = jnp.concatenate([cos_h, cos_h], axis=-1)
        sin2 = jnp.concatenate([sin_h, sin_h], axis=-1)
        return (xf * cos2 + _rotate_half(xf) * sin2).astype(xa.dtype)

    def impl(qa, ka):
        return rotate_one(qa), rotate_one(ka)

    return dispatch("llama_rope", impl, (q, k))


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0):
    """RoPE with reference parity semantics
    (``python/paddle/incubate/nn/functional/fused_rotary_position_embedding.py``
    over ``paddle/phi/kernels/fusion/gpu/fused_rope_utils.h``).

    q/k/v: [B, S, H, D] (or [S, B, H, D] when ``time_major``).

    Conventions (verified against the reference kernel + its unit test
    ``test/legacy_test/test_fused_rotary_position_embedding.py``):

    - Internally-built tables use the INTERLEAVED layout — adjacent slots
      share a frequency: table[j] uses exponent (j//2*2)/D
      (``fused_rope_utils.h`` VectorizedGetSinCos, flag_sin_cos=false).
      User tables are consumed in the same layout, element-by-element.
    - ``use_neox_rotary_style=True``: adjacent-pair rotation
      out[2i]   = x[2i]*cos[2i]   - x[2i+1]*sin[2i]
      out[2i+1] = x[2i+1]*cos[2i+1] + x[2i]*sin[2i+1]
      (RotateEveryTwo kernel; test ``mult_qkv``).
    - ``use_neox_rotary_style=False``: rotate_half
      out = x*cos + concat(-x[D/2:], x[:D/2])*sin
      (RotateHalf kernel; test ``mult_qkv_rotate_half``).

    Every tensor passed (q, and optionally k and v) is rotated; the return
    matches the inputs that were given.
    """
    seq_axis = 0 if time_major else 1
    if sin is None or cos is None:
        d = q.shape[-1]
        s = q.shape[seq_axis]
        # interleaved table: exponent (j//2*2)/d for slot j
        exps = (jnp.arange(d, dtype=jnp.float32) // 2) * 2.0 / d
        inv_freq = 1.0 / (rotary_emb_base ** exps)          # [D]
        t = jnp.arange(s, dtype=jnp.float32)
        emb = jnp.outer(t, inv_freq)                        # [S, D]
        cos_arr = jnp.cos(emb)
        sin_arr = jnp.sin(emb)
    else:
        cos_arr = cos._value if hasattr(cos, "_value") else jnp.asarray(cos)
        sin_arr = sin._value if hasattr(sin, "_value") else jnp.asarray(sin)
        cos_arr = cos_arr.reshape(-1, cos_arr.shape[-1]).astype(jnp.float32)
        sin_arr = sin_arr.reshape(-1, sin_arr.shape[-1]).astype(jnp.float32)

    if position_ids is not None:
        pos = position_ids._value if hasattr(position_ids, "_value") \
            else jnp.asarray(position_ids)
        cos_t = cos_arr[pos.astype(jnp.int32)]              # [B, S, D]
        sin_t = sin_arr[pos.astype(jnp.int32)]
        if time_major:
            cos_t = jnp.swapaxes(cos_t, 0, 1)
            sin_t = jnp.swapaxes(sin_t, 0, 1)
        cos_t = cos_t[:, :, None, :]
        sin_t = sin_t[:, :, None, :]
    else:
        if time_major:
            cos_t = cos_arr[:, None, None, :]
            sin_t = sin_arr[:, None, None, :]
        else:
            cos_t = cos_arr[None, :, None, :]
            sin_t = sin_arr[None, :, None, :]

    rotate = _rotate_every_two if use_neox_rotary_style else _rotate_half

    present = [t_ for t_ in (q, k, v) if t_ is not None]

    def rotate_one(xa):
        xf = xa.astype(jnp.float32)
        return (xf * cos_t + rotate(xf) * sin_t).astype(xa.dtype)

    def impl(*arrs):
        outs = tuple(rotate_one(a) for a in arrs)
        return outs if len(outs) > 1 else outs[0]

    return dispatch("fused_rope", impl, tuple(present))


rotary_position_embedding = fused_rotary_position_embedding


def fused_dropout_add(x, y, p=0.0, training=True, mode="upscale_in_train",
                      name=None):
    from ....nn.functional.common import dropout
    return dropout(x, p, training=training, mode=mode) + y


def masked_multihead_attention(x, cache_kv=None, src_mask=None,
                               sequence_lengths=None, num_heads=None,
                               **kwargs):
    """Fused decode-step attention (reference:
    ``python/paddle/incubate/nn/functional/masked_multihead_attention.py``
    over ``paddle/phi/kernels/fusion/gpu/masked_multihead_attention_kernel``):
    one new token per sequence attends over a growing KV cache.

    x: [B, 3*H*D] packed qkv for the current step.
    cache_kv: [2, B, H, max_seq, D] (k/v planes, written at the step slot).
    sequence_lengths: [B] int — how many tokens are already cached (the
    new token is written at this index).  Defaults to 0 (first step).
    src_mask: optional additive mask [B, 1, 1, max_seq] (or broadcastable).

    Returns (out [B, H*D], updated cache_kv).  Static-shape: the cache
    stays [max_seq] and masking hides future slots — the TPU-friendly
    formulation of the reference's in-place growing cache.
    """
    if cache_kv is None:
        raise ValueError("masked_multihead_attention requires cache_kv")
    max_seq = cache_kv.shape[3]
    h = cache_kv.shape[2]
    d = cache_kv.shape[4]
    if num_heads is not None and num_heads != h:
        raise ValueError(
            f"num_heads ({num_heads}) != cache heads ({h})")
    if x.shape[-1] != 3 * h * d:
        raise ValueError(
            f"x last dim ({x.shape[-1]}) != 3*H*D ({3 * h * d})")

    tensors = [x, cache_kv]
    has_mask = src_mask is not None
    if has_mask:
        tensors.append(src_mask)
    has_len = sequence_lengths is not None
    if has_len:
        tensors.append(sequence_lengths)

    def impl(xa, cache, *rest):
        r = list(rest)
        mask = r.pop(0) if has_mask else None
        seq_lens = (r.pop(0).astype(jnp.int32) if has_len
                    else jnp.zeros((xa.shape[0],), jnp.int32))
        b = xa.shape[0]
        # cache-full guard: the new token must have a slot; clamp writes
        # to the last slot (callers keep seq_lens < max_seq, the
        # reference precondition)
        seq_lens = jnp.minimum(seq_lens, max_seq - 1)
        qkv = xa.reshape(b, 3, h, d)
        q, k_new, v_new = qkv[:, 0], qkv[:, 1], qkv[:, 2]  # [B, H, D]
        # write the new k/v at each sequence's current slot (one-hot
        # scatter keeps shapes static for XLA)
        slot = jax.nn.one_hot(seq_lens, max_seq, dtype=cache.dtype)
        k_cache = cache[0] * (1.0 - slot[:, None, :, None]) + \
            k_new[:, :, None, :] * slot[:, None, :, None]
        v_cache = cache[1] * (1.0 - slot[:, None, :, None]) + \
            v_new[:, :, None, :] * slot[:, None, :, None]
        # attend over slots [0, seq_len] (the just-written one included)
        logits = jnp.einsum("bhd,bhsd->bhs", q, k_cache) / jnp.sqrt(
            jnp.asarray(d, jnp.float32)).astype(q.dtype)
        positions = jnp.arange(max_seq)[None, :]
        valid = positions <= seq_lens[:, None]            # [B, S]
        logits = jnp.where(valid[:, None, :], logits, -1e30)
        if mask is not None:
            # mask is [B|1, 1, 1, L] with L <= max_seq (the reference's
            # growing-length mask) or broadcastable: collapse the middle
            # singleton dims and right-pad to max_seq with zeros — the
            # valid-position mask above already hides slots beyond each
            # sequence's length, so the pad value never reaches softmax
            m = jnp.asarray(mask)
            m = m.reshape(m.shape[0], 1, m.shape[-1])
            if m.shape[-1] > max_seq:
                m = m[..., :max_seq]
            elif m.shape[-1] < max_seq:
                m = jnp.pad(m, ((0, 0), (0, 0),
                                (0, max_seq - m.shape[-1])))
            logits = logits + m.astype(logits.dtype)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1) \
            .astype(q.dtype)
        out = jnp.einsum("bhs,bhsd->bhd", probs, v_cache)
        new_cache = jnp.stack([k_cache, v_cache], axis=0)
        return out.reshape(b, h * d), new_cache

    nondiff = [False, False] + ([True] * (len(tensors) - 2))
    return dispatch("masked_multihead_attention", impl, tuple(tensors),
                    nondiff_mask=nondiff)


def fused_linear_cross_entropy(hidden, weight, labels, ignore_index=-100,
                               chunk=2048, name=None):
    """lm_head matmul + softmax cross-entropy, chunked over tokens so the
    full ``[N, vocab]`` logits tensor is NEVER materialized (at Llama
    bench scale that tensor is 16k x 32k: 1 GB bf16 / 2 GB fp32 per
    materialization, several HBM sweeps with the separate
    lm_head->log_softmax->NLL pipeline).

    Reference analogue: the fused softmax-cross-entropy path
    (``python/paddle/distributed/fleet/layers/mpu/mp_ops.py:410``
    ``_c_softmax_with_cross_entropy``'s memory story, single-device
    form).  TPU formulation: ``lax.scan`` over token chunks of the
    hidden states; each iteration computes chunk logits (bf16 matmul,
    fp32 accumulation), the fp32 log-sum-exp, and the label NLL, under
    ``jax.checkpoint`` so backward recomputes chunk logits instead of
    storing them.  Peak extra memory = one chunk of logits.

    hidden: [N, H] (or [B, S, H], flattened); weight: [H, V];
    labels: [N] int.  Returns the mean NLL over non-ignored tokens.
    """
    # Tensors pass through to dispatch UNWRAPPED ONLY THERE — the tape
    # records the op from the Tensor args (pre-unwrapping here would
    # silently disconnect eager backward)
    from ....core.dispatch import dispatch

    def impl(ha, wa, la):
        n = 1
        for s in ha.shape[:-1]:
            n *= s
        hf = ha.reshape(n, ha.shape[-1])
        lf = la.reshape(n).astype(jnp.int32)
        c = min(chunk, n)
        if n % c:
            # pad to a whole number of chunks; padded rows are ignored
            pad = c - n % c
            hf = jnp.concatenate(
                [hf, jnp.zeros((pad, hf.shape[-1]), hf.dtype)])
            lf = jnp.concatenate(
                [lf, jnp.full((pad,), ignore_index, jnp.int32)])
        hc = hf.reshape(-1, c, hf.shape[-1])
        lc = lf.reshape(-1, c)

        @jax.checkpoint
        def chunk_nll(h_c, l_c):
            logits = jax.lax.dot_general(
                h_c, wa, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)      # [c, V] fp32
            lse = jax.scipy.special.logsumexp(logits, axis=-1)
            idx = jnp.clip(l_c, 0, wa.shape[-1] - 1)
            picked = jnp.take_along_axis(
                logits, idx[:, None], axis=1)[:, 0]
            valid = l_c != ignore_index
            nll = jnp.where(valid, lse - picked, 0.0)
            return jnp.sum(nll), jnp.sum(valid)

        def body(carry, xs):
            s_nll, s_cnt = carry
            h_c, l_c = xs
            nll, cnt = chunk_nll(h_c, l_c)
            return (s_nll + nll, s_cnt + cnt), None

        (total, count), _ = jax.lax.scan(
            body, (jnp.float32(0.0), jnp.int32(0)), (hc, lc))
        return total / jnp.maximum(count, 1).astype(jnp.float32)

    return dispatch("fused_linear_cross_entropy", impl,
                    (hidden, weight, labels),
                    nondiff_mask=[False, False, True])


def memory_efficient_attention(query, key, value, attn_bias=None, p=0.0,
                               scale=None, training=True):
    """Memory-efficient attention == flash attention on TPU."""
    return scaled_dot_product_attention(query, key, value,
                                        attn_mask=attn_bias, dropout_p=p)


def fused_bias_act(x, bias=None, act_method="gelu", **kwargs):
    from ....nn import functional as F
    act = {"gelu": F.gelu, "relu": F.relu, "silu": F.silu,
           "swiglu": swiglu}[act_method]
    if bias is not None:
        x = x + bias
    return act(x)
