"""Fused transformer layers (analogue of
python/paddle/incubate/nn/layer/fused_transformer.py)."""

from __future__ import annotations

from ...nn.layer.layers import Layer
from ...nn.layer.transformer import MultiHeadAttention
from ...nn.layer.common import Linear
from ...nn import functional as F


class FusedMultiHeadAttention(MultiHeadAttention):
    """Fused QKV attention: same math, one dispatch through the flash path."""


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 activation="relu", **kwargs):
        super().__init__()
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.dropout_rate = dropout_rate
        self.activation = activation

    def forward(self, x):
        act = {"relu": F.relu, "gelu": F.gelu}[self.activation]
        h = act(self.linear1(x))
        h = F.dropout(h, self.dropout_rate, training=self.training)
        return self.linear2(h)
