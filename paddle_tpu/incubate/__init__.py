"""paddle_tpu.incubate (analogue of python/paddle/incubate/)."""

from . import nn  # noqa: F401
from . import autograd  # noqa: F401
from . import asp  # noqa: F401
from . import optimizer  # noqa: F401
