"""ASP — automatic structured (n:m) sparsity.

Capability analogue of ``paddle.incubate.asp``
(reference: python/paddle/incubate/asp/{asp.py,utils.py}): compute n:m
sparse masks for Linear/Conv weights (`prune_model`), keep them enforced
through training by masking after each optimizer step (`decorate`), with
per-layer exclusion lists and density reporting.

TPU note: n:m masks are plain elementwise multiplies that XLA fuses into
the producing matmul; the mask pattern follows the reference's mask_1d
(best-n-of-m along the input dimension).
"""

from __future__ import annotations

import weakref
from typing import Dict

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...nn import Layer, Linear
from ...nn.layer.conv import Conv2D

__all__ = ["decorate", "prune_model", "set_excluded_layers",
           "reset_excluded_layers", "calculate_density",
           "check_sparsity", "create_mask"]

# model -> {param full-name: numpy mask} (weak keys: entries die with the
# model, and a recycled id can never alias a dead model's state)
_MASKS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
# id(parameter Tensor) -> (weakref, mask); set_value mutates in place so the
# id is stable while the param lives, and the weakref guards against id
# reuse after a pruned model is garbage-collected
_PARAM_MASKS: Dict[int, tuple] = {}
_EXCLUDED: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def calculate_density(x) -> float:
    arr = np.asarray(x._value if isinstance(x, Tensor) else x)
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


def create_mask(weight: np.ndarray, n: int = 2, m: int = 4) -> np.ndarray:
    """mask_1d: within every group of m along the last axis keep the n
    largest magnitudes (reference utils.get_mask_1d)."""
    w = np.asarray(weight)
    flat = w.reshape(-1, w.shape[-1])
    cols = flat.shape[1]
    pad = (-cols) % m
    if pad:
        flat = np.concatenate(
            [flat, np.zeros((flat.shape[0], pad), flat.dtype)], axis=1)
    groups = flat.reshape(flat.shape[0], -1, m)
    order = np.argsort(np.abs(groups), axis=-1)  # ascending
    mask = np.ones_like(groups, dtype=np.float32)
    drop = order[:, :, :m - n]
    np.put_along_axis(mask, drop, 0.0, axis=-1)
    mask = mask.reshape(flat.shape[0], -1)[:, :cols]
    return mask.reshape(w.shape)


def create_mask_2d(weight: np.ndarray, n: int = 2, m: int = 4) -> np.ndarray:
    """mask_2d_greedy: within every m x m block keep entries greedily by
    magnitude such that every row AND column of the block keeps at most n
    (reference utils.get_mask_2d_greedy)."""
    w = np.abs(np.asarray(weight, np.float64))
    if w.ndim != 2:
        raise ValueError("mask_2d requires a 2-D weight view")
    rows, cols = w.shape
    pr, pc = (-rows) % m, (-cols) % m
    wp = np.pad(w, ((0, pr), (0, pc)))
    mask = np.zeros_like(wp, np.float32)
    for bi in range(0, wp.shape[0], m):
        for bj in range(0, wp.shape[1], m):
            block = wp[bi:bi + m, bj:bj + m]
            order = np.dstack(np.unravel_index(
                np.argsort(-block, axis=None), block.shape))[0]
            rcount = np.zeros(m, np.int32)
            ccount = np.zeros(m, np.int32)
            for r, c in order:
                if rcount[r] < n and ccount[c] < n:
                    mask[bi + r, bj + c] = 1.0
                    rcount[r] += 1
                    ccount[c] += 1
    return mask[:rows, :cols]


def check_sparsity(weight, n: int = 2, m: int = 4) -> bool:
    w = np.asarray(weight._value if isinstance(weight, Tensor) else weight)
    flat = w.reshape(-1, w.shape[-1])
    cols = flat.shape[1] - flat.shape[1] % m
    groups = flat[:, :cols].reshape(flat.shape[0], -1, m)
    return bool(np.all(np.count_nonzero(groups, axis=-1) <= n))


def set_excluded_layers(model: Layer, layer_names):
    _EXCLUDED.setdefault(model, set()).update(layer_names)


def reset_excluded_layers(model: Layer = None):
    if model is None:
        _EXCLUDED.clear()
    else:
        _EXCLUDED.pop(model, None)


def _supported(sub: Layer) -> bool:
    return isinstance(sub, (Linear, Conv2D))


def prune_model(model: Layer, n: int = 2, m: int = 4,
                mask_algo: str = "mask_1d", with_mask: bool = True):
    """Compute and apply n:m masks to every supported layer's weight.

    Returns {param_name: mask}; masks are remembered so a decorated
    optimizer keeps enforcing them.
    """
    if mask_algo not in ("mask_1d", "mask_2d_greedy", "mask_2d_best"):
        raise ValueError(f"unknown mask_algo {mask_algo!r}")
    # mask_2d_best degrades to greedy (the reference's exhaustive search
    # differs only in block-permutation enumeration)
    mask_fn = create_mask if mask_algo == "mask_1d" else create_mask_2d
    # evict records of garbage-collected params so the registry is bounded
    for pid in [pid for pid, (ref, _) in _PARAM_MASKS.items()
                if ref() is None]:
        del _PARAM_MASKS[pid]
    excluded = _EXCLUDED.get(model, set())
    masks = _MASKS.setdefault(model, {})
    for lname, sub in model.named_sublayers():
        if not _supported(sub) or lname in excluded:
            continue
        w = sub.weight
        arr = np.asarray(w._value)
        # mask along the input dim: for Linear [in, out] that is axis 0,
        # so transpose; for Conv [out, in, kh, kw] flatten per out-channel.
        if isinstance(sub, Linear):
            mask = mask_fn(arr.T, n, m).T
        else:
            oc = arr.shape[0]
            mask = mask_fn(arr.reshape(oc, -1), n, m).reshape(arr.shape)
        w.set_value(jnp.asarray(arr * mask, dtype=w._value.dtype))
        masks[f"{lname}.weight"] = mask
        _PARAM_MASKS[id(w)] = (weakref.ref(w), mask)
    return dict(masks)


def decorate(optimizer):
    """Wrap an optimizer so every ``step`` re-applies the stored masks to
    pruned parameters (reference ASPHelper decorate/OptimizerWithSparsity
    Guarantee)."""
    return _ASPOptimizer(optimizer)


class _ASPOptimizer:
    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def step(self):
        self._inner.step()
        if not _PARAM_MASKS:
            return
        for p in self._inner._parameter_list:
            entry = _PARAM_MASKS.get(id(p))
            if entry is None:
                continue
            ref, mask = entry
            if ref() is not p:  # stale id from a collected model
                del _PARAM_MASKS[id(p)]
                continue
            p.set_value(jnp.asarray(np.asarray(p._value) * mask,
                                    dtype=p._value.dtype))
