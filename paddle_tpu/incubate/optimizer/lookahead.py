"""LookAhead optimizer (arXiv:1907.08610; reference
python/paddle/incubate/optimizer/lookahead.py): every k inner steps the
slow weights move toward the fast weights by alpha, and the fast weights
are reset to the slow weights."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

__all__ = ["LookAhead"]


class LookAhead:
    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if inner_optimizer is None:
            raise ValueError("inner_optimizer cannot be None")
        if not 0.0 <= alpha <= 1.0:
            raise ValueError(f"alpha must be in [0, 1], got {alpha}")
        if not (isinstance(k, int) and k > 0):
            raise ValueError(f"k must be a positive integer, got {k}")
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._step_count = 0
        # slow weights snapshot at construction (reference initializes the
        # slow copies from the current params)
        self._slow = {id(p): np.asarray(p._value).copy()
                      for p in inner_optimizer._parameter_list}

    def __getattr__(self, name):
        if name == "inner_optimizer":  # empty instance dict (unpickling)
            raise AttributeError(name)
        return getattr(self.inner_optimizer, name)

    def step(self):
        self.inner_optimizer.step()
        self._step_count += 1
        if self._step_count % self.k:
            return
        for p in self.inner_optimizer._parameter_list:
            slow = self._slow.get(id(p))
            if slow is None:  # param added after construction
                slow = np.asarray(p._value).copy()
            fast = np.asarray(p._value)
            slow = slow + self.alpha * (fast - slow)
            self._slow[id(p)] = slow
            p.set_value(jnp.asarray(slow, dtype=p._value.dtype))

    def clear_grad(self, set_to_zero=True):
        self.inner_optimizer.clear_grad(set_to_zero)

    def state_dict(self):
        """Round-trippable: slow weights are saved per parameter index
        (the reference keeps them as optimizer accumulators for the same
        reason)."""
        sd = self.inner_optimizer.state_dict()
        sd["@LOOKAHEAD_step"] = self._step_count
        for i, p in enumerate(self.inner_optimizer._parameter_list):
            slow = self._slow.get(id(p))
            if slow is not None:
                sd[f"@LOOKAHEAD_slow_{i}"] = np.asarray(slow)
        return sd

    def set_state_dict(self, state_dict):
        state_dict = dict(state_dict)
        self._step_count = int(state_dict.pop("@LOOKAHEAD_step", 0))
        for i, p in enumerate(self.inner_optimizer._parameter_list):
            slow = state_dict.pop(f"@LOOKAHEAD_slow_{i}", None)
            if slow is not None:
                self._slow[id(p)] = np.asarray(slow)
        self.inner_optimizer.set_state_dict(state_dict)
