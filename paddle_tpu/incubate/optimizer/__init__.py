"""Incubate optimizers (reference: ``python/paddle/incubate/optimizer/
{lookahead.py,modelaverage.py}``): LookAhead slow/fast weights and
ModelAverage EMA-style parameter averaging with apply/restore."""

from .lookahead import LookAhead
from .modelaverage import ModelAverage
from .lars_momentum import LarsMomentumOptimizer
from .gradient_merge import GradientMergeOptimizer

__all__ = ["LookAhead", "ModelAverage", "LarsMomentumOptimizer",
           "GradientMergeOptimizer"]
