"""Incubate optimizers (reference: ``python/paddle/incubate/optimizer/
{lookahead.py,modelaverage.py}``): LookAhead slow/fast weights and
ModelAverage EMA-style parameter averaging with apply/restore."""

from .lookahead import LookAhead
from .modelaverage import ModelAverage

__all__ = ["LookAhead", "ModelAverage"]
