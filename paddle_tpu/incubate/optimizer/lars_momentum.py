"""LARS momentum optimizer (reference:
``python/paddle/incubate/optimizer/lars_momentum.py:22`` over the
``lars_momentum`` kernel).

Update rule (reference docstring):

    local_lr = lr * lars_coeff * ||p|| / (||g|| + lars_weight_decay*||p||)
    velocity = mu * velocity + local_lr * (g + lars_weight_decay * p)
    p        = p - velocity

When either norm is zero the local lr falls back to the global lr (the
kernel's guard).  ``exclude_from_weight_decay`` drops the decay term (but
keeps LARS scaling) for matching parameter names.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ...optimizer.optimizer import Optimizer

__all__ = ["LarsMomentumOptimizer"]


@functools.partial(jax.jit, donate_argnums=(0, 2),
                   static_argnames=("with_decay",))
def _lars_update(p, g, vel, lr, mu, coeff, wd, eps, rescale, with_decay):
    gf = g.astype(jnp.float32) * rescale
    pf = p.astype(jnp.float32)
    p_norm = jnp.sqrt(jnp.sum(pf * pf))
    g_norm = jnp.sqrt(jnp.sum(gf * gf))
    wd_t = wd if with_decay else 0.0
    denom = g_norm + wd_t * p_norm + eps
    local_lr = jnp.where((p_norm > 0) & (g_norm > 0),
                         lr * coeff * p_norm / denom, lr)
    v_new = mu * vel + local_lr * (gf + wd_t * pf)
    p_new = pf - v_new
    return p_new.astype(p.dtype), v_new


class LarsMomentumOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameter_list=None,
                 parameters=None, regularization=None, grad_clip=None,
                 name=None, exclude_from_weight_decay=None, epsilon=0,
                 multi_precision=False, rescale_grad=1.0):
        super().__init__(learning_rate, parameters or parameter_list,
                         None, grad_clip, name, multi_precision)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay
        self._exclude = list(exclude_from_weight_decay or [])
        self._epsilon = epsilon
        self._rescale_grad = rescale_grad

    def _create_accumulators(self, p):
        self._add_accumulator("velocity", p, dtype=jnp.float32)

    def _with_decay(self, p) -> bool:
        name = getattr(p, "name", "") or ""
        return not any(token in name for token in self._exclude)

    def _append_optimize_op(self, p, grad, lr_, wd):
        vel = self._get_accumulator("velocity", p)
        p_new, v_new = _lars_update(
            p._value, grad, vel, jnp.float32(lr_),
            jnp.float32(self._momentum), jnp.float32(self._lars_coeff),
            jnp.float32(self._lars_weight_decay),
            jnp.float32(self._epsilon), jnp.float32(self._rescale_grad),
            self._with_decay(p))
        p._value = p_new
        self._set_accumulator("velocity", p, v_new)
