"""Gradient merge — micro-batch gradient accumulation (reference:
``python/paddle/distributed/fleet/meta_optimizers/gradient_merge_optimizer.py``
over ``paddle/fluid/optimizer GradientMergeOptimizer``).

Eager semantics: call ``step()`` after every micro-batch ``backward()``;
gradients accumulate into fp32 buffers and the inner optimizer applies
them every ``k_steps`` calls (averaged when ``avg``).  Between merges the
parameters do not move, mirroring the reference's conditional update
block.  For the fully-compiled path see
``paddle_tpu.jit.TrainStep(accumulate_steps=k)``.
"""

from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor

__all__ = ["GradientMergeOptimizer"]


class GradientMergeOptimizer:
    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        if k_steps < 1:
            raise ValueError(f"k_steps must be >= 1, got {k_steps}")
        self._inner = inner_optimizer
        self._k = k_steps
        self._avg = avg
        self._count = 0
        self._acc = {}  # id(param) -> fp32 accumulation buffer

    # passthrough surface used by training loops
    @property
    def inner_optimizer(self):
        return self._inner

    def get_lr(self):
        return self._inner.get_lr()

    def set_lr(self, lr):
        return self._inner.set_lr(lr)

    def state_dict(self):
        return self._inner.state_dict()

    def set_state_dict(self, state):
        return self._inner.set_state_dict(state)

    def clear_grad(self, set_to_zero=True):
        return self._inner.clear_grad(set_to_zero)

    @property
    def _parameter_list(self):
        return self._inner._parameter_list

    def step(self):
        self._count += 1
        merge_now = self._count % self._k == 0
        for p in self._inner._parameter_list:
            if p.grad is None:
                continue
            g = p.grad._value.astype(jnp.float32)
            key = id(p)
            self._acc[key] = g if key not in self._acc else self._acc[key] + g
        if not merge_now:
            # swallow this micro-batch's grads so the inner optimizer never
            # sees partial sums (reference zeroes grads in the cond block)
            self._inner.clear_grad()
            return
        scale = 1.0 / self._k if self._avg else 1.0
        for p in self._inner._parameter_list:
            acc = self._acc.pop(id(p), None)
            if acc is None:
                continue
            p._grad = Tensor((acc * scale).astype(p._value.dtype))
        self._inner.step()
        self._inner.clear_grad()
