"""ModelAverage (reference python/paddle/incubate/optimizer/
modelaverage.py): maintains running sums of parameter values over a
sliding window; ``apply()`` swaps averaged weights in for evaluation and
``restore()`` puts the trained weights back."""

from __future__ import annotations

from contextlib import contextmanager

import jax.numpy as jnp
import numpy as np

__all__ = ["ModelAverage"]


class ModelAverage:
    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        if parameters is None:
            raise ValueError("ModelAverage requires an explicit parameter "
                             "list in this framework")
        self.avg_rate = average_window_rate
        self.min_window = min_average_window
        self.max_window = max_average_window
        self._params = list(parameters)
        self._sum = {id(p): np.zeros_like(np.asarray(p._value),
                                          dtype=np.float64)
                     for p in self._params}
        self._count = 0
        self._total_steps = 0
        self._backup = None

    def _window(self) -> int:
        """Reference num_updates rule: window grows with training length
        (rate * total steps), clamped to [min, max]."""
        w = int(self._total_steps * self.avg_rate)
        return max(self.min_window, min(self.max_window, max(w, 1)))

    def step(self):
        """Accumulate the current parameter values (call after
        optimizer.step()).  The window restarts once ``_window()`` samples
        have accumulated, keeping the running average as one sample."""
        self._count += 1
        self._total_steps += 1
        for p in self._params:
            self._sum[id(p)] += np.asarray(p._value, dtype=np.float64)
        if self._count >= self._window():
            for p in self._params:
                self._sum[id(p)] = self._sum[id(p)] / self._count
            self._count = 1

    def apply(self, executor=None, need_restore=True):
        """Swap averaged weights in (context-manager friendly)."""
        if self._count == 0:
            raise RuntimeError("ModelAverage.apply() before any step()")
        if self._backup is not None:
            raise RuntimeError(
                "ModelAverage.apply() called twice without restore(); the "
                "trained weights would be lost")
        self._backup = {id(p): np.asarray(p._value).copy()
                        for p in self._params}
        for p in self._params:
            avg = self._sum[id(p)] / self._count
            p.set_value(jnp.asarray(avg, dtype=p._value.dtype))
        if need_restore:
            return self._restore_ctx()
        return None

    @contextmanager
    def _restore_ctx(self):
        try:
            yield
        finally:
            self.restore()

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p in self._params:
            p.set_value(jnp.asarray(self._backup[id(p)],
                                    dtype=p._value.dtype))
        self._backup = None
