"""Functional/higher-order autograd (analogue of
python/paddle/incubate/autograd/primapi.py).  The jax transforms ARE the
primitive system here — no separate prim op set is needed."""

from ...autograd.functional import hessian, jacobian, jvp, vjp

__all__ = ["jvp", "vjp", "jacobian", "hessian", "grad"]


def grad(outputs, inputs, grad_outputs=None):
    from ...core.tape import grad as _g
    return _g(outputs, inputs, grad_outputs)
