"""MoE-aware global-norm gradient clipping.

Reference parity: ``python/paddle/incubate/distributed/models/moe/
grad_clip.py`` (ClipGradForMOEByGlobalNorm): expert parameters live only
on their expert-parallel rank, so their squared norms must be summed
across the moe group before being combined with the (replicated)
non-expert norm — clipping every rank with the same global norm.
"""

from __future__ import annotations

import jax.numpy as jnp

from .....core.tensor import Tensor
from .....nn.clip import ClipGradByGlobalNorm

__all__ = ["ClipGradForMOEByGlobalNorm"]


def _is_expert_param(p, is_expert_param_func=None):
    if is_expert_param_func is not None:
        return bool(is_expert_param_func(p))
    return bool(getattr(p, "_is_expert", False) or
                "expert" in (getattr(p, "name", "") or ""))


class ClipGradForMOEByGlobalNorm(ClipGradByGlobalNorm):
    """Global-norm clip where expert-parameter norms are all-reduced over
    ``moe_group`` before combining:
    ``global_norm = sqrt(norm_normal^2 + sum_group(norm_expert^2))``."""

    def __init__(self, clip_norm, is_expert_param_func=None, moe_group=None,
                 group_name="default_moe_group"):
        super().__init__(clip_norm, group_name)
        self._is_expert = is_expert_param_func
        self._moe_group = moe_group

    def _clip(self, params_grads):
        normal, expert = [], []
        for p, g in params_grads:
            (expert if _is_expert_param(p, self._is_expert)
             else normal).append((p, g))
        sq_normal = self._global_norm_sq(normal)
        sq_expert = self._global_norm_sq(expert)
        if sq_normal is None and sq_expert is None:
            return params_grads
        if sq_expert is not None and self._moe_group is not None \
                and getattr(self._moe_group, "nranks", 1) > 1:
            # all_reduce mutates the tensor in place and returns a task
            from .....distributed import all_reduce
            t = Tensor(sq_expert)
            all_reduce(t, group=self._moe_group)
            sq_expert = t._value
        sq = (sq_normal if sq_normal is not None else 0.0) + \
             (sq_expert if sq_expert is not None else 0.0)
        return self._apply_scale(params_grads, sq)
