from .gate import GShardGate, NaiveGate, SwitchGate, TopKGate
from .moe_layer import MoELayer
from .grad_clip import ClipGradForMOEByGlobalNorm

__all__ = ["MoELayer", "NaiveGate", "SwitchGate", "GShardGate", "TopKGate",
           "ClipGradForMOEByGlobalNorm"]
