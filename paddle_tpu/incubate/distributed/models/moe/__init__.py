from .gate import GShardGate, NaiveGate, SwitchGate, TopKGate
from .moe_layer import MoELayer

__all__ = ["MoELayer", "NaiveGate", "SwitchGate", "GShardGate", "TopKGate"]
