"""MoE gates (analogue of incubate/distributed/models/moe/gate/
{naive_gate,switch_gate,gshard_gate}.py).

Each gate returns (combine_weights [T,E,C], dispatch_mask [T,E,C] bool,
aux_loss scalar) in the dense GShard formulation — the layout the TPU MoE
dispatch consumes (one big einsum instead of the reference's
global_scatter/global_gather all-to-all ops; under an expert-sharded mesh
GSPMD lowers the einsum to exactly that all-to-all).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .....nn.layer.layers import Layer
from .....nn.layer.common import Linear


def _rank_positions(top_idx, num_experts, capacity, dtype=jnp.float32):
    """Per-slot capacity positions for top-k routing: position of each
    token within its expert's buffer = its arrival rank among tokens
    routed to that expert (cumsum over the token dim, earlier slots
    count first).  -> (positions [T, k] int32, keeps [T, k] bool,
    onehots list of [T, E]).  THE routing rank semantics — shared by
    the dense [T,E,C] dispatch and the sparse route() so the two paths
    cannot diverge."""
    k = top_idx.shape[1]
    prev = jnp.zeros((num_experts,), dtype)
    poss, keeps, onehots = [], [], []
    for slot in range(k):
        onehot = jax.nn.one_hot(top_idx[:, slot], num_experts, dtype=dtype)
        pos_in_e = jnp.cumsum(onehot, axis=0) - onehot + prev[None]
        prev = prev + jnp.sum(onehot, axis=0)
        pos = jnp.sum(pos_in_e * onehot, axis=1).astype(jnp.int32)
        poss.append(pos)
        keeps.append(pos < capacity)
        onehots.append(onehot)
    return poss, keeps, onehots


def _dense_dispatch(gates, top_idx, top_gates, num_experts, capacity):
    """Build combine/dispatch tensors from top-k assignments.

    gates: [T, E] softmax probs; top_idx/top_gates: [T, k].
    """
    k = top_idx.shape[1]
    poss, keeps, onehots = _rank_positions(top_idx, num_experts, capacity,
                                           gates.dtype)
    combine = jnp.zeros((gates.shape[0], num_experts, capacity),
                        gates.dtype)
    for slot in range(k):
        onehot = onehots[slot]
        g = top_gates[:, slot]
        pos, keep = poss[slot], keeps[slot]
        pos_oh = jax.nn.one_hot(jnp.where(keep, pos, capacity), capacity + 1,
                                dtype=gates.dtype)[:, :capacity]  # [T, C]
        combine = combine + (g * keep)[:, None, None] * \
            onehot[:, :, None] * pos_oh[:, None, :]
    dispatch = combine > 0
    return combine, dispatch


class TopKGate(Layer):
    def __init__(self, d_model, num_experts, top_k=2, capacity_factor=1.25,
                 weight_attr=None, dropless=False):
        """``dropless=True``: expert capacity = num_tokens, so NO token is
        ever dropped regardless of routing skew — exact MoE at the cost of
        an [E, T, D] dispatch buffer (use for small/medium T*E; the
        capacity-factor mode is the GShard production setting where
        overflow tokens are dropped by construction)."""
        super().__init__()
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.dropless = dropless
        self.gate = Linear(d_model, num_experts, weight_attr=weight_attr,
                           bias_attr=False)

    def capacity(self, num_tokens):
        if self.dropless:
            return int(num_tokens)
        cap = int(self.capacity_factor * num_tokens * self.top_k /
                  self.num_experts)
        return max(cap, self.top_k)

    def forward(self, x):
        from .....core.dispatch import dispatch as _dispatch
        num_experts = self.num_experts
        top_k = self.top_k
        capacity = self.capacity(x.shape[0] * (x.shape[1] if x.ndim == 3 else 1))

        def impl(hidden, w):
            flat = hidden.reshape(-1, hidden.shape[-1])
            logits = flat @ w
            gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
            top_gates, top_idx = jax.lax.top_k(gates, top_k)
            # renormalize top-k gate weights
            top_gates = top_gates / jnp.maximum(
                jnp.sum(top_gates, -1, keepdims=True), 1e-9)
            combine, disp = _dense_dispatch(gates, top_idx, top_gates,
                                            num_experts, capacity)
            # GShard aux loss: E * sum_e (mean gate_e * mean routed_e).
            # me is differentiable through softmax; ce (routing counts) is a
            # constant of the argmax. Returned in slot 1 so the tape keeps it
            # attached (slot order: differentiable outputs first).
            me = jnp.mean(gates, axis=0)
            ce = jnp.mean(
                jax.nn.one_hot(top_idx[:, 0], num_experts,
                               dtype=gates.dtype), axis=0)
            aux = num_experts * jnp.sum(me * ce)
            return combine.astype(hidden.dtype), aux.astype(jnp.float32), disp

        combine, aux, disp = _dispatch("moe_gate", impl,
                                       (x, self.gate.weight),
                                       n_diff_outputs=2)
        return combine, disp, aux

    def route(self, x):
        """Sparse routing (the Megablocks-style alternative to the dense
        [T, E, C] tensors): returns (eid [T,k], pos [T,k], w [T,k],
        keep [T,k] bool, aux) with the SAME rank/capacity semantics as
        ``forward`` — position = the token's arrival rank in its
        expert's buffer, ``keep`` false for overflow.  The [T, E, C]
        one-hots are never built: dispatch/combine become gather/scatter
        instead of einsums whose FLOPs rival the experts themselves
        (2*T*E*C*D — measured in BASELINE.md's MoE table)."""
        from .....core.dispatch import dispatch as _dispatch
        num_experts = self.num_experts
        top_k = self.top_k
        capacity = self.capacity(
            x.shape[0] * (x.shape[1] if x.ndim == 3 else 1))

        def impl(hidden, wg):
            flat = hidden.reshape(-1, hidden.shape[-1])
            logits = flat @ wg
            gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
            top_gates, top_idx = jax.lax.top_k(gates, top_k)
            top_gates = top_gates / jnp.maximum(
                jnp.sum(top_gates, -1, keepdims=True), 1e-9)
            poss, keeps, _ = _rank_positions(top_idx, num_experts,
                                             capacity)
            me = jnp.mean(gates, axis=0)
            ce = jnp.mean(jax.nn.one_hot(top_idx[:, 0], num_experts,
                                         dtype=gates.dtype), axis=0)
            aux = num_experts * jnp.sum(me * ce)
            return (top_gates.astype(hidden.dtype),
                    aux.astype(jnp.float32),
                    top_idx.astype(jnp.int32),
                    jnp.stack(poss, axis=1),
                    jnp.stack(keeps, axis=1))

        w, aux, eid, pos, keep = _dispatch("moe_gate_route", impl,
                                           (x, self.gate.weight),
                                           n_diff_outputs=2)
        return eid, pos, w, keep, aux


class NaiveGate(TopKGate):
    """Top-k softmax gate without aux loss emphasis (reference naive_gate)."""

    def __init__(self, d_model, num_expert=None, world_size=1, top_k=2,
                 capacity_factor=1.25):
        super().__init__(d_model, (num_expert or 1) * world_size, top_k,
                         capacity_factor)


class SwitchGate(TopKGate):
    """Top-1 switch routing (reference switch_gate)."""

    def __init__(self, d_model, num_expert=None, world_size=1, top_k=1,
                 capacity_factor=1.25):
        if top_k != 1:
            raise ValueError("SwitchGate routes top-1 by definition")
        super().__init__(d_model, (num_expert or 1) * world_size, 1,
                         capacity_factor)


class GShardGate(TopKGate):
    """Top-2 gating with load-balance loss (reference gshard_gate)."""

    def __init__(self, d_model, num_expert=None, world_size=1, top_k=2,
                 capacity_factor=2.0):
        super().__init__(d_model, (num_expert or 1) * world_size, top_k,
                         capacity_factor)
