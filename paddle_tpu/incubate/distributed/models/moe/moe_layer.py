"""MoELayer — expert-parallel mixture of experts.

Analogue of ``python/paddle/incubate/distributed/models/moe/moe_layer.py``
(MoEScatter:99, MoEGather:149, MoELayer:263).  TPU-native formulation:
instead of explicit ``global_scatter``/``global_gather`` all-to-all ops, the
dispatch/combine are dense einsums over [tokens, experts, capacity]; expert
weights are stacked [E, ...] and annotated over a mesh axis, so GSPMD lowers
the einsum pair to the all-to-all + local expert compute the reference codes
by hand — one definition serves 1 chip and an EP-sharded pod.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from .....core.dispatch import dispatch as _dispatch
from .....nn.layer.layers import Layer
from .gate import TopKGate


def _expert_ffn(x, wi, bi, wo, bo, act_name):
    """Batched per-expert FFN on [E, C, D] buffers (shared by all three
    dispatch paths)."""
    h = jnp.einsum("ecd,edf->ecf", x, wi) + bi
    if act_name == "gelu":
        h = jax.nn.gelu(h)
    elif act_name == "relu":
        h = jax.nn.relu(h)
    elif act_name == "silu":
        h = jax.nn.silu(h)
    return jnp.einsum("ecf,efd->ecd", h, wo) + bo


class MoELayer(Layer):
    """Mixture-of-experts FFN block.

    experts are stacked parameter sets applied with one batched einsum
    (MXU-friendly); ``expert_axis`` names the mesh axis to shard the expert
    dim over (the reference's EP group; None = let GSPMD decide).
    """

    def __init__(self, d_model, d_hidden, num_experts, top_k=2,
                 capacity_factor=1.25, gate: Optional[Layer] = None,
                 activation: str = "gelu", expert_axis: Optional[str] = None,
                 dropless: bool = False, dispatch_mode: Optional[str] = None,
                 name=None):
        super().__init__()
        if dispatch_mode not in (None, "scatter", "dense"):
            raise ValueError(
                f"dispatch_mode must be scatter/dense/None, got "
                f"{dispatch_mode!r}")
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        self.activation = activation
        self.expert_axis = expert_axis
        self.gate = gate or TopKGate(d_model, num_experts, top_k,
                                     capacity_factor, dropless=dropless)
        # Dispatch-mode selection follows the MEASURED crossover
        # (BASELINE.md round-4 on-chip sweep, T=8192/D=2048/F=8192/bf16):
        # the dense [T,E,C] einsums cost 2*T*E*C*D FLOPs each but run at
        # full MXU rate, and they beat the HBM-bound scatter only in the
        # narrow band cf~1.25 with E<=16 (16.6 vs 21.8 ms at E=8, 16.9
        # vs 20.5 at E=16); scatter wins at E=32 (13.6 vs 17.1), at
        # cf=1.0 (11.4 vs 13.0), at cf=2.0 (17.7 vs 26.0), and always on
        # memory ([E,C+1,D] vs two [T,E,C] one-hots).  Dense remains the
        # only path for custom gates without route()/capacity().
        gate_routes = hasattr(self.gate, "route") and \
            hasattr(self.gate, "capacity")
        if dispatch_mode == "scatter" and not gate_routes:
            raise ValueError(
                "dispatch_mode='scatter' needs a gate with "
                "route()/capacity() (TopKGate subclasses); this gate "
                "only implements the dense forward contract")
        if dispatch_mode is None:
            if not gate_routes:
                dispatch_mode = "dense"
            else:
                cf = getattr(self.gate, "capacity_factor", capacity_factor)
                dense_band = (1.0 < float(cf) < 1.5
                              and num_experts <= 16
                              and not getattr(self.gate, "dropless",
                                              dropless))
                dispatch_mode = "dense" if dense_band else "scatter"
        self.dispatch_mode = dispatch_mode
        from .....nn.initializer import XavierUniform
        init = XavierUniform()
        self.w_in = self.create_parameter((num_experts, d_model, d_hidden),
                                          default_initializer=init)
        self.w_out = self.create_parameter((num_experts, d_hidden, d_model),
                                           default_initializer=init)
        self.b_in = self.create_parameter((num_experts, 1, d_hidden),
                                          is_bias=True)
        self.b_out = self.create_parameter((num_experts, 1, d_model),
                                           is_bias=True)
        # mark expert params by name so ClipGradForMOEByGlobalNorm's
        # default predicate ("expert" in name) classifies them correctly
        for attr in ("w_in", "w_out", "b_in", "b_out"):
            getattr(self, attr).name = f"moe_expert_{attr}"
        if expert_axis is not None:
            from .....distributed.topology import get_global_mesh
            mesh = get_global_mesh()
            for p in (self.w_in, self.w_out, self.b_in, self.b_out):
                spec = PartitionSpec(expert_axis,
                                     *([None] * (p._value.ndim - 1)))
                p._dist_attr = spec
                if mesh is not None and expert_axis in mesh.axis_names:
                    p._value = jax.device_put(p._value,
                                              NamedSharding(mesh, spec))
        self.last_aux_loss = None

    def forward(self, x):
        if self.dispatch_mode == "scatter":
            return self._forward_scatter(x)
        combine, dispatch_mask, aux = self.gate(x)
        self.last_aux_loss = aux
        act_name = self.activation

        def impl(hidden, comb, disp, wi, bi, wo, bo):
            orig_shape = hidden.shape
            flat = hidden.reshape(-1, orig_shape[-1])  # [T, D]
            # dispatch: [E, C, D] = disp^T . tokens
            expert_in = jnp.einsum("tec,td->ecd", disp.astype(flat.dtype),
                                   flat)
            expert_out = _expert_ffn(expert_in, wi, bi, wo, bo, act_name)
            # combine: [T, D]
            out = jnp.einsum("tec,ecd->td", comb.astype(flat.dtype),
                             expert_out)
            return out.reshape(orig_shape)

        return _dispatch(
            "moe_layer", impl,
            (x, combine, dispatch_mask, self.w_in, self.b_in, self.w_out,
             self.b_out),
            nondiff_mask=[False, False, True, False, False, False, False])

    def _forward_scatter(self, x):
        """Sparse dispatch: scatter tokens into the [E, C, D] expert
        buffers by (expert id, capacity rank), batched expert matmuls,
        gather+weight to combine.  O(T*k*D) dispatch/combine HBM traffic
        instead of the dense path's 2*T*E*C*D einsum FLOPs; identical
        routing/drop semantics (same gate ranks).  With ``expert_axis``
        on a live mesh the dispatch runs EP-sharded (shard_map +
        collectives — the reference's global_scatter/global_gather
        dataflow, ``moe_utils.py:20``)."""
        if self.expert_axis is not None:
            from .....distributed.topology import get_global_mesh
            mesh = get_global_mesh()
            if mesh is not None and self.expert_axis in mesh.axis_names:
                p = mesh.shape[self.expert_axis]
                tokens = 1
                for dim in x.shape[:-1]:
                    tokens *= dim
                # shard_map needs both the expert dim and the token dim
                # evenly divisible; otherwise stay on the local path
                if p > 1 and self.num_experts % p == 0 \
                        and tokens % p == 0:
                    return self._forward_scatter_sharded(x, mesh, p)
        eid, pos, w, keep, aux = self.gate.route(x)
        self.last_aux_loss = aux
        act_name = self.activation
        num_experts = self.num_experts
        capacity = self.gate.capacity(
            x.shape[0] * (x.shape[1] if x.ndim == 3 else 1))

        def impl(hidden, wgt, eida, posa, keepa, wi, bi, wo, bo):
            orig_shape = hidden.shape
            flat = hidden.reshape(-1, orig_shape[-1])      # [T, D]
            t = flat.shape[0]
            k = eida.shape[1]
            tok = jnp.repeat(jnp.arange(t), k)             # [T*k]
            eidf = eida.reshape(-1)
            # dropped tokens land in a C-th overflow row, sliced away
            posf = jnp.where(keepa.reshape(-1), posa.reshape(-1), capacity)
            buf = jnp.zeros((num_experts, capacity + 1, flat.shape[-1]),
                            flat.dtype)
            buf = buf.at[eidf, posf].set(flat[tok])
            expert_in = buf[:, :capacity]                  # [E, C, D]
            expert_out = _expert_ffn(expert_in, wi, bi, wo, bo, act_name)
            # combine: gather each slot's row, weight, zero the dropped
            picked = expert_out[eida, posa]                # [T, k, D]
            wmask = (wgt * keepa.astype(wgt.dtype))[..., None]
            out = jnp.sum(picked * wmask.astype(picked.dtype), axis=1)
            return out.reshape(orig_shape)

        return _dispatch(
            "moe_layer_scatter", impl,
            (x, w, eid, pos, keep, self.w_in, self.b_in, self.w_out,
             self.b_out),
            nondiff_mask=[False, False, True, True, True,
                          False, False, False, False])

    def _forward_scatter_sharded(self, x, mesh, p):
        """EP-sharded scatter dispatch (reference
        ``moe_layer.py:99/:149`` MoEScatter/MoEGather over
        ``global_scatter``/``global_gather``, ``moe_utils.py:20``).

        TPU formulation of the all-to-all dataflow: the gate routes
        GLOBALLY (positions are ranks over all tokens, so every
        (expert, slot<C) pair has exactly one owner), then under
        ``shard_map`` over the ``ep`` axis:

        - each rank position-scatters its local tokens into a full
          [E, C, D] send buffer (other ranks' slots stay zero), and a
          ``psum_scatter`` over the expert dim delivers [E/P, C, D]
          per rank — summing one non-zero contribution per slot, this
          IS ``global_scatter`` with static shapes (E*C*D bytes/rank on
          ICI = cf * the ragged ideal);
        - local experts run on their [E/P, C, D] batch;
        - ``all_gather`` over the expert dim returns [E, C, D] and each
          rank gathers/weights its own tokens' rows — ``global_gather``.

        Exact parity with the single-device scatter path: same gate
        ranks, same slot assignment, and each slot is one token's value
        (the psum adds zeros), so results match bit-for-bit.
        """
        eid, pos, w, keep, aux = self.gate.route(x)
        self.last_aux_loss = aux
        act_name = self.activation
        num_experts = self.num_experts
        axis = self.expert_axis
        capacity = self.gate.capacity(
            x.shape[0] * (x.shape[1] if x.ndim == 3 else 1))

        def impl(hidden, wgt, eida, posa, keepa, wi, bi, wo, bo):
            orig_shape = hidden.shape
            flat = hidden.reshape(-1, orig_shape[-1])      # [T, D]
            kk = eida.shape[1]
            eidf = eida.reshape(-1, kk)
            posf = posa.reshape(-1, kk)
            keepf = keepa.reshape(-1, kk)
            wgtf = wgt.reshape(-1, kk)

            def inner(flat_l, wgt_l, eid_l, pos_l, keep_l,
                      wi_l, bi_l, wo_l, bo_l):
                t_l = flat_l.shape[0]
                tok = jnp.repeat(jnp.arange(t_l), kk)
                slot = jnp.where(keep_l.reshape(-1), pos_l.reshape(-1),
                                 capacity)
                send = jnp.zeros((num_experts, capacity + 1,
                                  flat_l.shape[-1]), flat_l.dtype)
                send = send.at[eid_l.reshape(-1), slot].set(flat_l[tok])
                send = send[:, :capacity]                  # [E, C, D]
                # global_scatter: one owner per slot -> reduce-scatter
                recv = jax.lax.psum_scatter(
                    send, axis, scatter_dimension=0, tiled=True)
                eout = _expert_ffn(recv, wi_l, bi_l, wo_l, bo_l, act_name)
                # global_gather: replicate expert outputs, local pick
                gath = jax.lax.all_gather(eout, axis, axis=0, tiled=True)
                picked = gath[eid_l, pos_l]                # [t_l, k, D]
                wmask = (wgt_l * keep_l.astype(wgt_l.dtype))[..., None]
                return jnp.sum(picked * wmask.astype(picked.dtype),
                               axis=1)

            tspec = PartitionSpec(axis, None)
            espec3 = PartitionSpec(axis, None, None)
            out = jax.shard_map(
                inner, mesh=mesh,
                in_specs=(tspec, tspec, tspec, tspec, tspec,
                          espec3, espec3, espec3, espec3),
                out_specs=tspec, axis_names={axis})(
                flat, wgtf, eidf, posf, keepf, wi, bi, wo, bo)
            return out.reshape(orig_shape)

        return _dispatch(
            "moe_layer_scatter_ep", impl,
            (x, w, eid, pos, keep, self.w_in, self.b_in, self.w_out,
             self.b_out),
            nondiff_mask=[False, False, True, True, True,
                          False, False, False, False])
