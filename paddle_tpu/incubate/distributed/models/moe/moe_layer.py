"""MoELayer — expert-parallel mixture of experts.

Analogue of ``python/paddle/incubate/distributed/models/moe/moe_layer.py``
(MoEScatter:99, MoEGather:149, MoELayer:263).  TPU-native formulation:
instead of explicit ``global_scatter``/``global_gather`` all-to-all ops, the
dispatch/combine are dense einsums over [tokens, experts, capacity]; expert
weights are stacked [E, ...] and annotated over a mesh axis, so GSPMD lowers
the einsum pair to the all-to-all + local expert compute the reference codes
by hand — one definition serves 1 chip and an EP-sharded pod.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from .....core.dispatch import dispatch as _dispatch
from .....nn.layer.layers import Layer
from .gate import TopKGate


class MoELayer(Layer):
    """Mixture-of-experts FFN block.

    experts are stacked parameter sets applied with one batched einsum
    (MXU-friendly); ``expert_axis`` names the mesh axis to shard the expert
    dim over (the reference's EP group; None = let GSPMD decide).
    """

    def __init__(self, d_model, d_hidden, num_experts, top_k=2,
                 capacity_factor=1.25, gate: Optional[Layer] = None,
                 activation: str = "gelu", expert_axis: Optional[str] = None,
                 dropless: bool = False, dispatch_mode: Optional[str] = None,
                 name=None):
        super().__init__()
        if dispatch_mode not in (None, "scatter", "dense"):
            raise ValueError(
                f"dispatch_mode must be scatter/dense/None, got "
                f"{dispatch_mode!r}")
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        self.activation = activation
        self.gate = gate or TopKGate(d_model, num_experts, top_k,
                                     capacity_factor, dropless=dropless)
        # scatter (Megablocks-style gather/matmul/scatter) is the
        # single-device default: the dense [T,E,C] dispatch einsums cost
        # 2*T*E*C*D FLOPs EACH — at bench scale that rivals the expert
        # matmuls themselves and grows with E (capacity-sweep table in
        # BASELINE.md).  The dense einsum remains the EP-sharded path
        # (GSPMD lowers it to the reference's all-to-all) and the path
        # for custom gates that only implement the dense forward
        # contract (no route()/capacity()).
        gate_routes = hasattr(self.gate, "route") and \
            hasattr(self.gate, "capacity")
        if dispatch_mode == "scatter" and not gate_routes:
            raise ValueError(
                "dispatch_mode='scatter' needs a gate with "
                "route()/capacity() (TopKGate subclasses); this gate "
                "only implements the dense forward contract")
        self.dispatch_mode = dispatch_mode or \
            ("scatter" if expert_axis is None and gate_routes else "dense")
        from .....nn.initializer import XavierUniform
        init = XavierUniform()
        self.w_in = self.create_parameter((num_experts, d_model, d_hidden),
                                          default_initializer=init)
        self.w_out = self.create_parameter((num_experts, d_hidden, d_model),
                                           default_initializer=init)
        self.b_in = self.create_parameter((num_experts, 1, d_hidden),
                                          is_bias=True)
        self.b_out = self.create_parameter((num_experts, 1, d_model),
                                           is_bias=True)
        # mark expert params by name so ClipGradForMOEByGlobalNorm's
        # default predicate ("expert" in name) classifies them correctly
        for attr in ("w_in", "w_out", "b_in", "b_out"):
            getattr(self, attr).name = f"moe_expert_{attr}"
        if expert_axis is not None:
            from .....distributed.topology import get_global_mesh
            mesh = get_global_mesh()
            for p in (self.w_in, self.w_out, self.b_in, self.b_out):
                spec = PartitionSpec(expert_axis,
                                     *([None] * (p._value.ndim - 1)))
                p._dist_attr = spec
                if mesh is not None and expert_axis in mesh.axis_names:
                    p._value = jax.device_put(p._value,
                                              NamedSharding(mesh, spec))
        self.last_aux_loss = None

    def forward(self, x):
        if self.dispatch_mode == "scatter":
            return self._forward_scatter(x)
        combine, dispatch_mask, aux = self.gate(x)
        self.last_aux_loss = aux
        act_name = self.activation

        def impl(hidden, comb, disp, wi, bi, wo, bo):
            orig_shape = hidden.shape
            flat = hidden.reshape(-1, orig_shape[-1])  # [T, D]
            # dispatch: [E, C, D] = disp^T . tokens
            expert_in = jnp.einsum("tec,td->ecd", disp.astype(flat.dtype),
                                   flat)
            h = jnp.einsum("ecd,edf->ecf", expert_in, wi) + bi
            if act_name == "gelu":
                h = jax.nn.gelu(h)
            elif act_name == "relu":
                h = jax.nn.relu(h)
            elif act_name == "silu":
                h = jax.nn.silu(h)
            expert_out = jnp.einsum("ecf,efd->ecd", h, wo) + bo
            # combine: [T, D]
            out = jnp.einsum("tec,ecd->td", comb.astype(flat.dtype),
                             expert_out)
            return out.reshape(orig_shape)

        return _dispatch(
            "moe_layer", impl,
            (x, combine, dispatch_mask, self.w_in, self.b_in, self.w_out,
             self.b_out),
            nondiff_mask=[False, False, True, False, False, False, False])

    def _forward_scatter(self, x):
        """Sparse dispatch: scatter tokens into the [E, C, D] expert
        buffers by (expert id, capacity rank), batched expert matmuls,
        gather+weight to combine.  O(T*k*D) dispatch/combine HBM traffic
        instead of the dense path's 2*T*E*C*D einsum FLOPs; identical
        routing/drop semantics (same gate ranks)."""
        eid, pos, w, keep, aux = self.gate.route(x)
        self.last_aux_loss = aux
        act_name = self.activation
        num_experts = self.num_experts
        capacity = self.gate.capacity(
            x.shape[0] * (x.shape[1] if x.ndim == 3 else 1))

        def impl(hidden, wgt, eida, posa, keepa, wi, bi, wo, bo):
            orig_shape = hidden.shape
            flat = hidden.reshape(-1, orig_shape[-1])      # [T, D]
            t = flat.shape[0]
            k = eida.shape[1]
            tok = jnp.repeat(jnp.arange(t), k)             # [T*k]
            eidf = eida.reshape(-1)
            # dropped tokens land in a C-th overflow row, sliced away
            posf = jnp.where(keepa.reshape(-1), posa.reshape(-1), capacity)
            buf = jnp.zeros((num_experts, capacity + 1, flat.shape[-1]),
                            flat.dtype)
            buf = buf.at[eidf, posf].set(flat[tok])
            expert_in = buf[:, :capacity]                  # [E, C, D]
            h = jnp.einsum("ecd,edf->ecf", expert_in, wi) + bi
            if act_name == "gelu":
                h = jax.nn.gelu(h)
            elif act_name == "relu":
                h = jax.nn.relu(h)
            elif act_name == "silu":
                h = jax.nn.silu(h)
            expert_out = jnp.einsum("ecf,efd->ecd", h, wo) + bo
            # combine: gather each slot's row, weight, zero the dropped
            picked = expert_out[eida, posa]                # [T, k, D]
            wmask = (wgt * keepa.astype(wgt.dtype))[..., None]
            out = jnp.sum(picked * wmask.astype(picked.dtype), axis=1)
            return out.reshape(orig_shape)

        return _dispatch(
            "moe_layer_scatter", impl,
            (x, w, eid, pos, keep, self.w_in, self.b_in, self.w_out,
             self.b_out),
            nondiff_mask=[False, False, True, True, True,
                          False, False, False, False])
