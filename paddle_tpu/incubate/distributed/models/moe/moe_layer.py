"""MoELayer — expert-parallel mixture of experts.

Analogue of ``python/paddle/incubate/distributed/models/moe/moe_layer.py``
(MoEScatter:99, MoEGather:149, MoELayer:263).  TPU-native formulation:
instead of explicit ``global_scatter``/``global_gather`` all-to-all ops, the
dispatch/combine are dense einsums over [tokens, experts, capacity]; expert
weights are stacked [E, ...] and annotated over a mesh axis, so GSPMD lowers
the einsum pair to the all-to-all + local expert compute the reference codes
by hand — one definition serves 1 chip and an EP-sharded pod.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from .....core.dispatch import dispatch as _dispatch
from .....nn.layer.layers import Layer
from .gate import TopKGate


class MoELayer(Layer):
    """Mixture-of-experts FFN block.

    experts are stacked parameter sets applied with one batched einsum
    (MXU-friendly); ``expert_axis`` names the mesh axis to shard the expert
    dim over (the reference's EP group; None = let GSPMD decide).
    """

    def __init__(self, d_model, d_hidden, num_experts, top_k=2,
                 capacity_factor=1.25, gate: Optional[Layer] = None,
                 activation: str = "gelu", expert_axis: Optional[str] = None,
                 dropless: bool = False, name=None):
        super().__init__()
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        self.activation = activation
        self.gate = gate or TopKGate(d_model, num_experts, top_k,
                                     capacity_factor, dropless=dropless)
        from .....nn.initializer import XavierUniform
        init = XavierUniform()
        self.w_in = self.create_parameter((num_experts, d_model, d_hidden),
                                          default_initializer=init)
        self.w_out = self.create_parameter((num_experts, d_hidden, d_model),
                                           default_initializer=init)
        self.b_in = self.create_parameter((num_experts, 1, d_hidden),
                                          is_bias=True)
        self.b_out = self.create_parameter((num_experts, 1, d_model),
                                           is_bias=True)
        # mark expert params by name so ClipGradForMOEByGlobalNorm's
        # default predicate ("expert" in name) classifies them correctly
        for attr in ("w_in", "w_out", "b_in", "b_out"):
            getattr(self, attr).name = f"moe_expert_{attr}"
        if expert_axis is not None:
            from .....distributed.topology import get_global_mesh
            mesh = get_global_mesh()
            for p in (self.w_in, self.w_out, self.b_in, self.b_out):
                spec = PartitionSpec(expert_axis,
                                     *([None] * (p._value.ndim - 1)))
                p._dist_attr = spec
                if mesh is not None and expert_axis in mesh.axis_names:
                    p._value = jax.device_put(p._value,
                                              NamedSharding(mesh, spec))
        self.last_aux_loss = None

    def forward(self, x):
        combine, dispatch_mask, aux = self.gate(x)
        self.last_aux_loss = aux
        act_name = self.activation

        def impl(hidden, comb, disp, wi, bi, wo, bo):
            orig_shape = hidden.shape
            flat = hidden.reshape(-1, orig_shape[-1])  # [T, D]
            # dispatch: [E, C, D] = disp^T . tokens
            expert_in = jnp.einsum("tec,td->ecd", disp.astype(flat.dtype),
                                   flat)
            h = jnp.einsum("ecd,edf->ecf", expert_in, wi) + bi
            if act_name == "gelu":
                h = jax.nn.gelu(h)
            elif act_name == "relu":
                h = jax.nn.relu(h)
            elif act_name == "silu":
                h = jax.nn.silu(h)
            expert_out = jnp.einsum("ecf,efd->ecd", h, wo) + bo
            # combine: [T, D]
            out = jnp.einsum("tec,ecd->td", comb.astype(flat.dtype),
                             expert_out)
            return out.reshape(orig_shape)

        return _dispatch(
            "moe_layer", impl,
            (x, combine, dispatch_mask, self.w_in, self.b_in, self.w_out,
             self.b_out),
            nondiff_mask=[False, False, True, False, False, False, False])
