"""HeterPS analogue: device-resident hot-embedding cache over a cold
store (reference ``paddle/fluid/framework/fleet/heter_ps/``: GPU-resident
HashTables for hot features, pull/push against the CPU/SSD parameter
server for the cold tail).

TPU-native form: the hot table is ONE dense jax array ``[hot_rows, dim]``
living in HBM (shardable over the mesh like any parameter), addressed
through a host-side id->slot hash map; cold ids fall through to a
:class:`paddle_tpu.distributed.ps.PSClient` (or an in-process dict when
none is given).  Admission is frequency-based: every ``sync_interval``
steps the most-frequent cold ids are promoted into HBM, evicting the
least-recently-promoted slots (their rows are flushed back to the cold
store first).  The hot path — gather + scatter-grad on the dense HBM
table — is pure XLA; only the cold tail pays host round-trips.
"""

from __future__ import annotations

from collections import Counter

import jax
import jax.numpy as jnp
import numpy as np

from ...autograd.py_layer import PyLayer
from ...core.tensor import Tensor
from ...nn import Layer

__all__ = ["HBMEmbedding"]


class _DictColdStore:
    """In-process cold store with PSClient's pull/push surface."""

    def __init__(self, dim, init_scale=0.01, seed=0):
        self.dim = dim
        self.init_scale = init_scale
        self.seed = seed
        self.rows = {}

    def _init_row(self, key):
        rng = np.random.default_rng(self.seed ^ (int(key) * 0x9E3779B9))
        return rng.uniform(-self.init_scale, self.init_scale,
                           self.dim).astype(np.float32)

    def pull(self, keys):
        out = np.empty((len(keys), self.dim), np.float32)
        for i, k in enumerate(keys):
            k = int(k)
            if k not in self.rows:
                self.rows[k] = self._init_row(k)
            out[i] = self.rows[k]
        return out

    def push_grad(self, keys, grads, lr):
        for k, g in zip(keys, grads):
            k = int(k)
            if k not in self.rows:
                self.rows[k] = self._init_row(k)
            self.rows[k] = self.rows[k] - lr * g

    def set_rows(self, keys, values):
        for k, v in zip(keys, values):
            self.rows[int(k)] = np.asarray(v, np.float32).copy()


class _PSColdStore:
    def __init__(self, client, table_id, dim):
        self.client = client
        self.table_id = table_id
        self.dim = dim

    def pull(self, keys):
        return self.client.pull_sparse(
            self.table_id, np.asarray(keys, np.uint64))

    def push_grad(self, keys, grads, lr):
        self.client.push_sparse_grad(
            self.table_id, np.asarray(keys, np.uint64),
            np.asarray(grads, np.float32), lr)

    def set_rows(self, keys, values):
        # write-back = push of (old - new)/lr is fragile; PS tables are
        # server-updated, so flushing evicted hot rows uses a lr=1 push of
        # the delta from the server's current values
        cur = self.pull(keys)
        delta = cur - np.asarray(values, np.float32)
        self.client.push_sparse_grad(
            self.table_id, np.asarray(keys, np.uint64), delta, 1.0)


class _HotLookup(PyLayer):
    """Differentiable gather on the HBM table; backward scatter-adds into
    the table's .grad so any optimizer updates the hot rows."""

    @staticmethod
    def forward(ctx, table, slots):
        slots_np = np.asarray(slots._value if isinstance(slots, Tensor)
                              else slots)
        ctx.save_for_backward(table)
        ctx.slots = slots_np
        out = jnp.take(table._value, jnp.asarray(slots_np), axis=0)
        return Tensor(out, stop_gradient=False)

    @staticmethod
    def backward(ctx, grad_out):
        (table,) = ctx.saved_tensor()
        g = grad_out._value if isinstance(grad_out, Tensor) \
            else jnp.asarray(grad_out)
        gt = jnp.zeros_like(table._value).at[
            jnp.asarray(ctx.slots)].add(g)
        return Tensor(gt), None


class HBMEmbedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, hot_rows=4096,
                 ps_client=None, table_id=0, learning_rate=0.01,
                 init_scale=0.01, sync_interval=100, seed=0):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.hot_rows = hot_rows
        self.learning_rate = learning_rate
        self.sync_interval = sync_interval
        if ps_client is not None:
            ps_client.create_sparse_table(table_id, embedding_dim,
                                          init_scale=init_scale, seed=seed)
            self.cold = _PSColdStore(ps_client, table_id, embedding_dim)
        else:
            self.cold = _DictColdStore(embedding_dim, init_scale, seed)
        # the HBM-resident hot table: a real Parameter (sharded like any
        # other under a mesh; optimizers update it locally)
        from ...nn.initializer import Uniform
        self.hot_table = self.create_parameter(
            (hot_rows, embedding_dim),
            default_initializer=Uniform(-init_scale, init_scale))
        self._slot_of = {}         # id -> hot slot
        self._id_of = {}           # hot slot -> id
        self._free = list(range(hot_rows))
        self._freq = Counter()     # admission statistics
        self._promo_order = []     # FIFO of occupied slots for eviction
        self._step = 0

    # -- cache bookkeeping ---------------------------------------------
    def _flush_slot(self, slot):
        old_id = self._id_of.pop(slot)
        del self._slot_of[old_id]
        row = np.asarray(self.hot_table._value[slot])
        self.cold.set_rows([old_id], [row])

    def _admit(self, ids):
        """Promote ids into free (or evicted) hot slots; load their rows
        from the cold store into the HBM table."""
        ids = [i for i in ids if i not in self._slot_of]
        if not ids:
            return
        rows = self.cold.pull(ids)
        slots = []
        for i in ids:
            if not self._free:
                victim = self._promo_order.pop(0)
                self._flush_slot(victim)
                self._free.append(victim)
            s = self._free.pop()
            self._slot_of[i] = s
            self._id_of[s] = i
            self._promo_order.append(s)
            slots.append(s)
        tbl = self.hot_table._value
        self.hot_table._value = tbl.at[jnp.asarray(slots)].set(
            jnp.asarray(rows))

    def sync_cache(self):
        """Admission pass: promote the hottest cold ids seen since the
        last sync (reference: pull_sparse_to_gpu build pass)."""
        if not self._freq:
            return
        budget = max(self.hot_rows // 4, 1)
        hottest = [i for i, _ in self._freq.most_common(budget)]
        self._admit(hottest)
        self._freq.clear()

    # -- forward --------------------------------------------------------
    def forward(self, ids):
        ids_np = np.asarray(ids._value if isinstance(ids, Tensor) else ids)
        flat = ids_np.reshape(-1)
        if flat.size == 0:
            return Tensor(jnp.zeros(
                tuple(ids_np.shape) + (self.embedding_dim,), jnp.float32))
        self._step += 1

        hot_mask = np.asarray([int(i) in self._slot_of for i in flat])
        cold_ids = flat[~hot_mask]
        self._freq.update(int(i) for i in cold_ids)
        if self._step % self.sync_interval == 0:
            self.sync_cache()
            hot_mask = np.asarray(
                [int(i) in self._slot_of for i in flat])
            cold_ids = flat[~hot_mask]

        parts = []
        if hot_mask.any():
            slots = np.asarray([self._slot_of[int(i)]
                                for i in flat[hot_mask]], np.int32)
            hot_rows = _HotLookup.apply(self.hot_table, Tensor(slots))
            parts.append(_expand_rows(
                hot_rows, np.nonzero(hot_mask)[0], flat.size))
        if (~hot_mask).any():
            cold_rows = self.cold.pull(list(cold_ids))
            cold_full = np.zeros((flat.size, self.embedding_dim),
                                 np.float32)
            cold_full[~hot_mask] = cold_rows
            parts.append(_ColdLookup.apply(
                Tensor(jnp.asarray(cold_full)), self._cold_hook(),
                self, cold_ids, np.nonzero(~hot_mask)[0], flat.size))
        result = parts[0] if len(parts) == 1 else parts[0] + parts[1]
        return result.reshape(list(ids_np.shape) + [self.embedding_dim])

    def _cold_hook(self):
        if not hasattr(self, "_hook_param"):
            self._hook_param = self.create_parameter([1], is_bias=True)
        return self._hook_param

    # introspection ------------------------------------------------------
    @property
    def resident_ids(self):
        return set(self._slot_of)


def _expand_rows(rows, scatter_idx, total):
    """Differentiable scatter of [k, d] rows into [total, d] zeros."""
    from ...core.dispatch import dispatch

    def impl(r, idx):
        return jnp.zeros((total, r.shape[-1]), r.dtype).at[idx].set(r)

    return dispatch("hbm_scatter_rows", impl, (rows, Tensor(scatter_idx)),
                    nondiff_mask=[False, True])


class _ColdLookup(PyLayer):
    """Cold rows enter as constants; backward pushes their grads to the
    cold store (the reference's push path for CPU-resident features)."""

    @staticmethod
    def forward(ctx, rows_full, hook, layer, cold_ids, positions, total):
        ctx.layer = layer
        ctx.cold_ids = cold_ids
        ctx.positions = positions
        return Tensor(rows_full._value, stop_gradient=False)

    @staticmethod
    def backward(ctx, grad_out):
        g = np.asarray(grad_out._value if isinstance(grad_out, Tensor)
                       else grad_out)
        layer = ctx.layer
        if ctx.cold_ids.size:
            grads = g[ctx.positions]
            # pre-sum duplicate cold ids
            order = np.argsort(ctx.cold_ids, kind="stable")
            keys_sorted = ctx.cold_ids[order]
            uniq, start = np.unique(keys_sorted, return_index=True)
            summed = np.add.reduceat(grads[order], start, axis=0)
            layer.cold.push_grad(list(uniq), summed, layer.learning_rate)
        return None, Tensor(np.zeros(1, np.float32))
