"""Incubating distributed components (reference incubate/distributed):
MoE models and the HeterPS-analogue HBM embedding cache."""

from . import models  # noqa: F401
from .heter_ps import HBMEmbedding

__all__ = ["models", "HBMEmbedding"]
