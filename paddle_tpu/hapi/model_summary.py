"""Standalone ``paddle.summary`` (reference: ``python/paddle/hapi/
model_summary.py``): per-layer table with output shapes (when an input
size is given) and parameter counts; returns the totals dict."""

from __future__ import annotations

import numpy as np

from ..nn import Layer

__all__ = ["summary"]


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    rows = []
    hooks = []

    def make_hook(name):
        def hook(layer, inputs, output):
            out = output[0] if isinstance(output, (tuple, list)) else output
            shape = list(getattr(out, "shape", [])) or ["-"]
            n_params = sum(p.size for p in layer.parameters(
                include_sublayers=False)) if hasattr(
                layer, "parameters") else 0
            rows.append((name, type(layer).__name__, shape, n_params))
        return hook

    if input_size is not None or input is not None:
        for name, sub in net.named_sublayers():
            hooks.append(sub.register_forward_post_hook(make_hook(name)))
        was_training = net.training
        net.eval()
        try:
            if input is None:
                import paddle_tpu as paddle
                dtype = (dtypes[0] if dtypes else "float32")
                input = paddle.to_tensor(
                    np.zeros(tuple(input_size), dtype))
            net(input)
        finally:
            for h in hooks:
                h.remove()
            if was_training:
                net.train()

    total = sum(p.size for p in net.parameters())
    trainable = sum(p.size for p in net.parameters()
                    if not p.stop_gradient)
    header = f"{'Layer':<32}{'Type':<24}{'Output Shape':<20}{'Params':>10}"
    print(header)
    print("-" * len(header))
    for name, tname, shape, n in rows:
        print(f"{name:<32}{tname:<24}{str(shape):<20}{n:>10}")
    print("-" * len(header))
    print(f"Total params: {total}")
    print(f"Trainable params: {trainable}")
    print(f"Non-trainable params: {total - trainable}")
    return {"total_params": int(total), "trainable_params": int(trainable)}
