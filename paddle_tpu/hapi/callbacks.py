"""Training callbacks (analogue of python/paddle/hapi/callbacks.py)."""

from __future__ import annotations

import time

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping",
           "LRScheduler", "config_callbacks"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """Training progress (reference hapi ProgBarLogger): verbose=1 is an
    in-place progress bar with ETA and samples/s; verbose=2 prints a
    line every ``log_freq`` steps with throughput."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def _fmt(self, logs):
        return ", ".join(f"{k}: {v:.4f}" if isinstance(v, float)
                         else f"{k}: {v}"
                         for k, v in (logs or {}).items())

    def _rate_eta(self):
        dur = max(time.time() - self._start, 1e-9)
        ips = None
        bs = self.params.get("batch_size")
        if bs:
            ips = self.steps * bs / dur
        total = self.params.get("steps")
        eta = None
        if total:
            eta = dur / max(self.steps, 1) * (total - self.steps)
        return ips, eta

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = 0
        self._start = time.time()

    def on_train_batch_end(self, step, logs=None):
        self.steps += 1
        if not self.verbose:
            return
        ips, eta = self._rate_eta()
        extra = ""
        if ips is not None:
            extra += f", {ips:.1f} samples/s"
        if eta is not None:
            extra += f", ETA {eta:.0f}s"
        if self.verbose == 1:
            total = self.params.get("steps")
            frac = f"{self.steps}/{total}" if total else f"{self.steps}"
            print(f"\repoch {self.epoch} [{frac}] "
                  f"{self._fmt(logs)}{extra}   ", end="", flush=True)
        elif self.steps % self.log_freq == 0:
            print(f"epoch {self.epoch} step {step}: "
                  f"{self._fmt(logs)}{extra}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose == 1:
            print()
        if self.verbose:
            dur = time.time() - self._start
            print(f"epoch {epoch} done in {dur:.1f}s: {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(f"{self.save_dir}/final")


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = min_delta
        self.best = None
        self.wait = 0
        self.stopped = False
        if mode == "auto":
            mode = "min" if "loss" in monitor or "err" in monitor else "max"
        self.mode = mode

    def on_eval_end(self, logs=None):
        logs = logs or {}
        value = logs.get(self.monitor)
        if value is None:
            return
        if isinstance(value, (list, tuple)):
            value = value[0]
        better = (self.best is None or
                  (value < self.best - self.min_delta if self.mode == "min"
                   else value > self.best + self.min_delta))
        if better:
            self.best = value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped = True
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched
        opt = getattr(self.model, "_optimizer", None)
        if opt is not None and isinstance(opt._learning_rate, Sched):
            return opt._learning_rate
        return None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if s is not None and self.by_step:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if s is not None and self.by_epoch:
            s.step()


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.append(ProgBarLogger(log_freq, verbose))
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    cl = CallbackList(cbks)
    cl.set_model(model)
    cl.set_params({"batch_size": batch_size, "epochs": epochs, "steps": steps,
                   "verbose": verbose, "metrics": metrics or []})
    return cl
