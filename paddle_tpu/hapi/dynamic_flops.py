"""FLOPs counting (reference: ``python/paddle/hapi/dynamic_flops.py``
``paddle.flops(net, input_size)``): forward-hook based per-layer MAC
counting for the common layer types, with a printable table."""

from __future__ import annotations

import numpy as np

from ..nn import Layer
from ..nn.layer.common import Linear
from ..nn.layer.conv import Conv1D, Conv2D, Conv3D
from ..nn.layer.norm import LayerNorm, _BatchNormBase
from ..nn.layer.pooling import AdaptiveAvgPool2D, AvgPool2D, MaxPool2D

__all__ = ["flops"]


def _numel(shape):
    n = 1
    for s in shape:
        n *= int(s)
    return n


def _conv_flops(layer, inp, out):
    # MACs = out_elems * (in_channels/groups * prod(kernel))
    kernel = _numel(layer.weight.shape[2:])
    cin_g = layer.weight.shape[1]
    return _numel(out.shape) * cin_g * kernel


def _linear_flops(layer, inp, out):
    return _numel(out.shape) * layer.weight.shape[0]


def _norm_flops(layer, inp, out):
    return 2 * _numel(inp.shape)


def _pool_flops(layer, inp, out):
    return _numel(inp.shape)


_HANDLERS = [
    ((Conv1D, Conv2D, Conv3D), _conv_flops),
    ((Linear,), _linear_flops),
    ((_BatchNormBase, LayerNorm), _norm_flops),
    ((AvgPool2D, MaxPool2D, AdaptiveAvgPool2D), _pool_flops),
]


def flops(net: Layer, input_size, custom_ops=None, print_detail=False):
    """Count forward MAC-FLOPs of ``net`` on a zero batch of
    ``input_size`` (reference ``paddle.flops``).  custom_ops:
    {LayerType: fn(layer, input, output) -> flops} extends/overrides the
    builtin handlers."""
    import paddle_tpu as paddle

    custom_ops = custom_ops or {}
    records = []
    hooks = []

    def make_hook(layer, handler):
        def hook(l, inputs, output):
            inp = inputs[0] if isinstance(inputs, (tuple, list)) else inputs
            records.append((type(layer).__name__,
                            int(handler(layer, inp, output))))
        return hook

    for sub in net.sublayers(include_self=True):
        handler = custom_ops.get(type(sub))
        if handler is None:
            for types, h in _HANDLERS:
                if isinstance(sub, types):
                    handler = h
                    break
        if handler is not None:
            hooks.append(sub.register_forward_post_hook(
                make_hook(sub, handler)))

    was_training = net.training
    net.eval()
    try:
        x = paddle.to_tensor(np.zeros(tuple(input_size), np.float32))
        net(x)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()

    total = sum(f for _, f in records)
    if print_detail:
        for name, f in records:
            print(f"  {name}: {f:,}")
    print(f"Total Flops: {total}     Total Params: "
          f"{sum(p.size for p in net.parameters())}")
    return total
