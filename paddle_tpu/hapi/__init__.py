"""paddle_tpu.hapi — high-level Model API (analogue of python/paddle/hapi)."""

from .model import Model
from . import callbacks  # noqa: F401

__all__ = ["Model", "callbacks"]
