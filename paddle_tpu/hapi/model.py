"""paddle.Model analogue (reference python/paddle/hapi/model.py, 2504 LoC).

fit/evaluate/predict drive the eager tape; `prepare(jit=True)` (TPU default)
swaps the inner train step for a fully-compiled TrainStep when the optimizer
supports it.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor
from ..io import DataLoader
from ..metric import Metric
from .callbacks import config_callbacks

__all__ = ["Model"]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs if isinstance(inputs, (list, tuple)) else (
            [inputs] if inputs is not None else None)
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self.stop_training = False
        self._train_step = None
        self._amp_level = None
        self._scaler = None

    # ---- configuration ----
    def prepare(self, optimizer=None, loss=None, metrics=None, jit=True,
                amp_configs=None):
        """``amp_configs``: "O1"/"O2" or a dict with "level" (+ optional
        GradScaler kwargs under "scaler") — reference Model.prepare's AMP
        contract.  O1 wraps the eager forward in auto_cast; O2 additionally
        runs the compiled step in bf16 with master weights."""
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, list) else [metrics]
        self._use_jit = jit
        if amp_configs is not None:
            if isinstance(amp_configs, str):
                self._amp_level = amp_configs
            else:
                self._amp_level = amp_configs.get("level", "O1")
            if self._amp_level not in ("O0", "O1", "O2"):
                raise ValueError(
                    f"amp level must be O0/O1/O2, got {self._amp_level!r}")
            if self._amp_level == "O2":
                self.network.to(dtype="bfloat16")
                if optimizer is not None:
                    optimizer._multi_precision = True
            if self._amp_level in ("O1",) and not jit:
                from .. import amp as _amp
                self._scaler = _amp.GradScaler()
        return self

    def _fleet_world(self):
        """Data-parallel process world when fleet/launch is active."""
        try:
            from ..distributed import get_world_size
            return get_world_size()
        except Exception:
            return 1

    def _make_loader(self, data, batch_size, shuffle, num_workers=0):
        if data is None or isinstance(data, DataLoader):
            return data
        if self._fleet_world() > 1:
            # fleet-aware fit: each process reads its shard (reference
            # hapi model distributed fit uses DistributedBatchSampler)
            from ..io import DistributedBatchSampler
            sampler = DistributedBatchSampler(
                data, batch_size=batch_size, shuffle=shuffle)
            return DataLoader(data, batch_sampler=sampler,
                              num_workers=num_workers)
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          num_workers=num_workers)

    def _compute_loss(self, outputs, labels):
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        lbls = labels if isinstance(labels, (list, tuple)) else [labels]
        if self._loss is None:
            return outs[0]
        return self._loss(*outs, *lbls)

    def _split_batch(self, batch):
        if isinstance(batch, (list, tuple)) and len(batch) >= 2:
            return batch[:-1], batch[-1:]
        return (batch,), ()

    def _update_train_metrics(self, outputs, labels):
        """Reference hapi computes metrics on TRAIN batches too; returns
        the accumulated values ([] when no metrics configured)."""
        if not self._metrics:
            return []
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        vals = []
        for m in self._metrics:
            res = m.compute(*outs, *labels)
            m.update(res)
            vals.append(m.accumulate())
        return vals

    # ---- steps ----
    def train_batch(self, inputs, labels=None, update=True):
        """Returns ``[loss]``, or ``([loss], metric_values)`` when
        metrics were configured in ``prepare`` (reference Model.train_batch
        contract).  In the compiled path the forward's outputs ride along
        as TrainStep aux outputs so metrics cost no second forward."""
        self.network.train()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if isinstance(labels, (list, tuple)) else (
            [labels] if labels is not None else [])
        if self._use_jit and self._train_step is None:
            from ..jit.train_step import TrainStep
            amp_level = self._amp_level
            want_outputs = bool(self._metrics)

            def loss_fn(net, *args):
                n_in = len(inputs)

                def run():
                    outs = net(*args[:n_in])
                    loss = self._compute_loss(outs, list(args[n_in:]))
                    if want_outputs:
                        outs_t = outs if isinstance(outs, (list, tuple)) \
                            else [outs]
                        return (loss,) + tuple(outs_t)
                    return loss

                if amp_level == "O1":
                    # the dispatch-level cast hook applies while TRACING,
                    # so O1 autocast composes with the compiled step (bf16
                    # matmuls, fp32 master math — no loss scaling needed
                    # for bf16)
                    from .. import amp as _amp
                    with _amp.auto_cast(level="O1"):
                        return run()
                return run()

            step = TrainStep(self.network, loss_fn, self._optimizer)
            if step._update_fn is not None:
                self._train_step = step
            else:
                self._train_step = False  # unsupported optimizer: eager path
        if self._train_step:
            out = self._train_step(*inputs, *labels)
            if isinstance(out, tuple):
                loss, outs = out[0], list(out[1:])
                metrics = self._update_train_metrics(outs, labels)
                return [float(np.asarray(loss._value))], metrics
            return [float(np.asarray(out._value))]
        if self._amp_level == "O1":
            from .. import amp as _amp
            with _amp.auto_cast(level="O1"):
                outputs = self.network(*inputs)
                loss = self._compute_loss(outputs, labels)
            if self._scaler is not None:
                scaled = self._scaler.scale(loss)
                scaled.backward()
                if update:
                    self._scaler.step(self._optimizer)
                    self._scaler.update()
                    self._optimizer.clear_grad()
                return self._train_result(loss, outputs, labels)
        else:
            outputs = self.network(*inputs)
            loss = self._compute_loss(outputs, labels)
        loss.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        return self._train_result(loss, outputs, labels)

    def _train_result(self, loss, outputs, labels):
        losses = [float(np.asarray(loss._value))]
        if self._metrics:
            return losses, self._update_train_metrics(outputs, labels)
        return losses

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        from ..core.tape import no_grad
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if isinstance(labels, (list, tuple)) else (
            [labels] if labels is not None else [])
        with no_grad():
            outputs = self.network(*inputs)
            loss = self._compute_loss(outputs, labels)
            metrics = []
            for m in self._metrics:
                outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
                res = m.compute(*outs, *labels)
                m.update(res)
                metrics.append(m.accumulate())
        return [float(np.asarray(loss._value))], metrics

    def predict_batch(self, inputs):
        self.network.eval()
        from ..core.tape import no_grad
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        with no_grad():
            outputs = self.network(*inputs)
        return outputs

    # ---- loops ----
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        loader = self._make_loader(train_data, batch_size, shuffle,
                                   num_workers)
        eval_loader = self._make_loader(eval_data, batch_size, False)
        try:
            n_steps = len(loader)
        except TypeError:
            n_steps = None
        # a user-supplied DataLoader carries its own batch size; fit's
        # batch_size argument only applied when WE built the loader
        eff_bs = batch_size
        if isinstance(train_data, DataLoader):
            eff_bs = getattr(train_data, "batch_size", None)
        cbks = config_callbacks(callbacks, model=self, epochs=epochs,
                                batch_size=eff_bs, steps=n_steps,
                                log_freq=log_freq, verbose=verbose,
                                save_dir=save_dir, save_freq=save_freq,
                                metrics=[n for m in self._metrics
                                         for n in (m.name() if isinstance(
                                             m.name(), list) else [m.name()])])
        self.stop_training = False
        cbks.on_train_begin()
        iters = 0
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, batch in enumerate(loader):
                cbks.on_train_batch_begin(step)
                ins, lbls = self._split_batch(batch)
                out = self.train_batch(list(ins), list(lbls))
                if isinstance(out, tuple):
                    losses, mvals = out
                else:
                    losses, mvals = out, []
                logs = {"loss": losses[0]}
                for m, v in zip(self._metrics, mvals):
                    names = m.name() if isinstance(m.name(), list) \
                        else [m.name()]
                    vals = v if isinstance(v, (list, tuple)) else [v]
                    for n, val in zip(names, vals):
                        logs[n] = val
                cbks.on_train_batch_end(step, logs)
                iters += 1
                if num_iters is not None and iters >= num_iters:
                    self.stop_training = True
                    break
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cbks.on_epoch_end(epoch, logs)
            if self.stop_training:
                break
        cbks.on_train_end(logs if "logs" in dir() else None)
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        loader = self._make_loader(eval_data, batch_size, False)
        for m in self._metrics:
            m.reset()
        losses = []
        for step, batch in enumerate(loader):
            ins, lbls = self._split_batch(batch)
            l, _ = self.eval_batch(list(ins), list(lbls))
            losses.append(l[0])
            if num_iters is not None and step + 1 >= num_iters:
                break
        logs = {"loss": float(np.mean(losses)) if losses else 0.0}
        for m in self._metrics:
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = m.accumulate()
            vals = vals if isinstance(vals, (list, tuple)) else [vals]
            for n, v in zip(names, vals):
                logs[n] = v
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = self._make_loader(test_data, batch_size, False)
        outputs = []
        for batch in loader:
            ins = batch if isinstance(batch, (list, tuple)) else [batch]
            outs = self.predict_batch(list(ins))
            outputs.append(outs)
        return outputs

    # ---- persistence / info ----
    def save(self, path, training=True):
        """training=True: checkpoint (params + optimizer state).
        training=False: export the INFERENCE artifact via jit.save using
        the InputSpecs given at construction (reference Model.save's
        dual behavior, hapi/model.py _save_inference_model)."""
        if not training:
            if self._inputs is None:
                raise ValueError(
                    "Model.save(training=False) exports an inference "
                    "model and needs input specs: Model(net, "
                    "inputs=[InputSpec(...)])")
            from ..jit.api import save as jit_save
            self.network.eval()
            jit_save(self.network, path, input_spec=list(self._inputs))
            return
        from ..framework.io import save as fsave
        fsave(self.network.state_dict(), path + ".pdparams")
        if self._optimizer is not None:
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as fload
        self.network.set_state_dict(fload(path + ".pdparams"))
        import os
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(fload(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .model_summary import summary as _summary
        return _summary(self.network, input_size=input_size,
                        dtypes=[dtype] if dtype else None)
