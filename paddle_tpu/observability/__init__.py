"""paddle_tpu.observability — always-on runtime metrics + structured
tracing.

Two cooperating layers (see the module docstrings for design notes):

- :mod:`~paddle_tpu.observability.metrics` — a process-wide
  ``MetricsRegistry`` of named Counter/Gauge/Histogram instruments with
  Prometheus-text and JSON exporters and a ``snapshot()``/
  ``diff_snapshots()`` API for benches.  The serving engine, TrainStep,
  the Pallas decode-attention routing gate and the kernel tuner record
  into the default registry.
- :mod:`~paddle_tpu.observability.spans` — ``span(name, **attrs)``
  ranges over ``runtime.HostTracer`` and ``merge_chrome_traces`` to
  stitch the host trace with the ``jax.profiler`` device dump into one
  Perfetto-loadable file.
- :mod:`~paddle_tpu.observability.flightrec` — the per-request
  ``FlightRecorder``: a bounded ring of structured lifecycle events
  the serving engine emits, with ``timeline()``/``explain()`` queries,
  a JSON export ``tools/explain_request.py`` reads, and per-request
  Perfetto lanes that ride ``merge_chrome_traces``.
- :mod:`~paddle_tpu.observability.fleet` — the FLEET plane over the
  router: ``stitch_flight_records`` correlates per-replica recorders
  into one cross-replica record (fleet ``explain()``, one Perfetto
  lane per replica), ``merge_registry_snapshots`` federates
  per-replica registries under a ``replica=`` label, and
  ``SLOBurnRateMonitor`` turns the ``serving.slo.*`` counters into
  windowed burn rates and replay-deterministic ``ALERT_KINDS``
  alerts.
- :mod:`~paddle_tpu.observability.timeseries` — the
  ``TimeSeriesRecorder``: bounded step-indexed instrument samples
  with windowed aggregates (rates, per-window hwm, histogram-delta
  quantiles) and JSON export.

The reference analogue is ``paddle/fluid/platform/profiler`` plus its
benchmark/stat utilities; here the metrics side is pull-model (scrape
or snapshot) so hot paths never block on an exporter.
"""

from .metrics import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, DEFAULT_BUCKETS, NAME_RE,
    diff_snapshots, get_registry,
)
from .spans import (  # noqa: F401
    format_span_name, instant, merge_chrome_traces, parse_span_name, span,
)
from .flightrec import (  # noqa: F401
    EVENT_KINDS, FlightEvent, FlightRecord, FlightRecorder,
    explain_events, load_flight_record,
)
from .fleet import (  # noqa: F401
    ALERT_KINDS, SLOBurnRateMonitor, StitchedEvent, StitchedRecord,
    merge_registry_snapshots, stitch_flight_records,
)
from .timeseries import TimeSeriesRecorder  # noqa: F401

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "DEFAULT_BUCKETS",
    "NAME_RE", "diff_snapshots", "get_registry",
    "span", "instant", "format_span_name", "parse_span_name",
    "merge_chrome_traces",
    "EVENT_KINDS", "FlightEvent", "FlightRecord", "FlightRecorder",
    "explain_events", "load_flight_record",
    "ALERT_KINDS", "SLOBurnRateMonitor", "StitchedEvent",
    "StitchedRecord", "merge_registry_snapshots",
    "stitch_flight_records", "TimeSeriesRecorder",
]
