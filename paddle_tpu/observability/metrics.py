"""Process-wide metrics registry (Counter / Gauge / Histogram).

The always-on observability substrate the reference provides through
``paddle/fluid/platform/profiler`` stat tables and benchmark counters,
rebuilt as a pull-model instrument registry: hot paths (serving
scheduler, train step, kernel dispatch gates) record into named
instruments; exporters render the registry as Prometheus text or JSON,
and ``snapshot()``/``diff_snapshots()`` give benches a cheap
before/after delta without resetting anything.

Design constraints (the serving decode loop runs instrument updates on
every scheduler iteration):

- **near-zero cost when disabled** — every mutator starts with one
  attribute load + bool test on the owning registry; no locking, no
  label resolution, no timestamping happens on the disabled path.
- **thread-safe** — one lock per instrument guards value mutation;
  registration holds the registry lock.  Reads for export take the same
  locks, so snapshots are internally consistent per instrument.
- **fixed-bucket histograms** — observation cost is a bisect over a
  static bound list; p50/p95/p99 are interpolated from the buckets at
  EXPORT time, never maintained online.

Instrument names must match ``^[a-z][a-z0-9_.]*$`` (dots namespace the
subsystem: ``serving.queue_depth``); the Prometheus exporter maps dots
to underscores.  Re-registering a name returns the existing instrument
when the type and label names agree and raises otherwise —
``tools/check_metrics_names.py`` lints the tree for both rules
statically.
"""

from __future__ import annotations

import bisect
import itertools
import json
import re
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

NAME_RE = re.compile(r"^[a-z][a-z0-9_.]*$")

# monotone registry ids: the stable dedupe identity (see
# ``MetricsRegistry.dedupe_key``)
_REGISTRY_UID = itertools.count()

# default buckets cover sub-ms kernel dispatch through multi-second
# request latencies (seconds)
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

_NO_LABELS = ()


def _esc_label_value(v) -> str:
    """Escape a label value for the ``k=v,k2=v2`` snapshot key so
    values containing ``,``/``=``/newlines cannot fabricate extra
    labels when the key is re-parsed (percent-encoding; inverse is
    ``_unesc_label_value``)."""
    return (str(v).replace("%", "%25").replace(",", "%2C")
            .replace("=", "%3D").replace("\n", "%0A"))


def _unesc_label_value(v: str) -> str:
    return (v.replace("%0A", "\n").replace("%3D", "=")
            .replace("%2C", ",").replace("%25", "%"))


def _label_key(label_names: Tuple[str, ...], label_values: Tuple) -> str:
    if not label_names:
        return ""
    return ",".join(f"{k}={_esc_label_value(v)}"
                    for k, v in zip(label_names, label_values))


class _Instrument:
    """Common instrument plumbing: identity, labels, child lookup."""

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str, help_str: str,
                 label_names: Tuple[str, ...]):
        self._reg = registry
        self.name = name
        self.help = help_str
        self.label_names = label_names
        self._lock = threading.Lock()

    def _resolve_labels(self, kwargs) -> Tuple:
        # deliberately NOT run on the disabled fast path (unlike the
        # cheap amount<0 check): sorting/comparing label names is real
        # work, and the disabled mode's contract is one attribute load
        # + bool test per call — mislabeled calls surface on enable
        if tuple(sorted(kwargs)) != tuple(sorted(self.label_names)):
            raise ValueError(
                f"{self.name}: labels {sorted(kwargs)} do not match "
                f"declared label names {sorted(self.label_names)}")
        return tuple(str(kwargs[k]) for k in self.label_names)


class Counter(_Instrument):
    """Monotonically increasing count (events, tokens, cache misses)."""

    kind = "counter"

    def __init__(self, registry, name, help_str="",
                 label_names: Tuple[str, ...] = _NO_LABELS):
        super().__init__(registry, name, help_str, label_names)
        self._vals: Dict[Tuple, float] = {}

    def inc(self, amount: float = 1, **labels):
        # validate BEFORE the enabled check: a buggy negative delta
        # must not pass silently in disabled mode only to start raising
        # when someone turns metrics on
        if amount < 0:
            raise ValueError(f"{self.name}: counter increment must be >= 0")
        if not self._reg._enabled:
            return
        key = self._resolve_labels(labels) if (labels or self.label_names) \
            else _NO_LABELS
        with self._lock:
            self._vals[key] = self._vals.get(key, 0) + amount

    def value(self, **labels) -> float:
        key = self._resolve_labels(labels) if (labels or self.label_names) \
            else _NO_LABELS
        with self._lock:
            return self._vals.get(key, 0)

    def total(self) -> float:
        """Sum over every label set (``== value()`` for an unlabeled
        counter) — the label-agnostic reading consumers like the
        serving engine's ``stats()`` delta need from a labeled
        counter, whose ``value()`` requires one exact label set."""
        with self._lock:
            return float(sum(self._vals.values()))

    def _snap(self) -> dict:
        with self._lock:
            vals = dict(self._vals)
        return {"type": self.kind, "help": self.help,
                "labels": list(self.label_names),
                "values": {_label_key(self.label_names, k): v
                           for k, v in vals.items()}}


class Gauge(_Instrument):
    """Point-in-time level (queue depth, slot occupancy).  Tracks a
    high-water mark alongside the current value (``hwm``) so peaks
    survive between scrapes."""

    kind = "gauge"

    def __init__(self, registry, name, help_str="",
                 label_names: Tuple[str, ...] = _NO_LABELS):
        super().__init__(registry, name, help_str, label_names)
        self._vals: Dict[Tuple, float] = {}
        self._hwm: Dict[Tuple, float] = {}

    def set(self, value: float, **labels):
        if not self._reg._enabled:
            return
        key = self._resolve_labels(labels) if (labels or self.label_names) \
            else _NO_LABELS
        with self._lock:
            self._vals[key] = value
            if value > self._hwm.get(key, float("-inf")):
                self._hwm[key] = value

    def add(self, delta: float, **labels):
        if not self._reg._enabled:
            return
        key = self._resolve_labels(labels) if (labels or self.label_names) \
            else _NO_LABELS
        with self._lock:
            v = self._vals.get(key, 0) + delta
            self._vals[key] = v
            if v > self._hwm.get(key, float("-inf")):
                self._hwm[key] = v

    def value(self, **labels) -> float:
        key = self._resolve_labels(labels) if (labels or self.label_names) \
            else _NO_LABELS
        with self._lock:
            return self._vals.get(key, 0)

    def hwm(self, **labels) -> float:
        key = self._resolve_labels(labels) if (labels or self.label_names) \
            else _NO_LABELS
        with self._lock:
            return self._hwm.get(key, 0)

    def _snap(self) -> dict:
        with self._lock:
            vals, hwm = dict(self._vals), dict(self._hwm)
        return {"type": self.kind, "help": self.help,
                "labels": list(self.label_names),
                "values": {_label_key(self.label_names, k): v
                           for k, v in vals.items()},
                "hwm": {_label_key(self.label_names, k): v
                        for k, v in hwm.items()}}


def _quantile_from_buckets(q: float, bounds: Sequence[float],
                           counts: Sequence[float]) -> float:
    """Prometheus-style histogram_quantile: linear interpolation inside
    the bucket holding the q-th observation; the +Inf bucket clamps to
    the largest finite bound."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    rank = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        prev_cum = cum
        cum += c
        if cum >= rank:
            if i >= len(bounds):            # +Inf bucket
                return float(bounds[-1]) if bounds else 0.0
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i]
            if c <= 0:
                return float(hi)
            return float(lo + (hi - lo) * (rank - prev_cum) / c)
    return float(bounds[-1]) if bounds else 0.0


class Histogram(_Instrument):
    """Fixed-bucket distribution with interpolated p50/p95/p99."""

    kind = "histogram"

    def __init__(self, registry, name, help_str="",
                 label_names: Tuple[str, ...] = _NO_LABELS,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(registry, name, help_str, label_names)
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError(f"{self.name}: histogram needs >= 1 bucket")
        self.bounds = bounds
        # per label-set: [bucket counts (len bounds + 1 for +Inf), count, sum]
        self._vals: Dict[Tuple, list] = {}

    def observe(self, value: float, **labels):
        if not self._reg._enabled:
            return
        key = self._resolve_labels(labels) if (labels or self.label_names) \
            else _NO_LABELS
        i = bisect.bisect_left(self.bounds, value)
        with self._lock:
            cell = self._vals.get(key)
            if cell is None:
                cell = [[0] * (len(self.bounds) + 1), 0, 0.0]
                self._vals[key] = cell
            cell[0][i] += 1
            cell[1] += 1
            cell[2] += value

    def summary(self, **labels) -> dict:
        key = self._resolve_labels(labels) if (labels or self.label_names) \
            else _NO_LABELS
        with self._lock:
            cell = self._vals.get(key)
            if cell is None:
                return {"count": 0, "sum": 0.0, "p50": 0.0, "p95": 0.0,
                        "p99": 0.0}
            counts, count, total = list(cell[0]), cell[1], cell[2]
        return {
            "count": count, "sum": total,
            "p50": _quantile_from_buckets(0.50, self.bounds, counts),
            "p95": _quantile_from_buckets(0.95, self.bounds, counts),
            "p99": _quantile_from_buckets(0.99, self.bounds, counts),
        }

    def _snap(self) -> dict:
        with self._lock:
            vals = {k: [list(c[0]), c[1], c[2]]
                    for k, c in self._vals.items()}
        out = {}
        for k, (counts, count, total) in vals.items():
            out[_label_key(self.label_names, k)] = {
                "count": count, "sum": total, "buckets": counts,
                "p50": _quantile_from_buckets(0.50, self.bounds, counts),
                "p95": _quantile_from_buckets(0.95, self.bounds, counts),
                "p99": _quantile_from_buckets(0.99, self.bounds, counts),
            }
        return {"type": self.kind, "help": self.help,
                "labels": list(self.label_names),
                "le": [*self.bounds], "values": out}


class MetricsRegistry:
    """Named instrument registry.  One process-wide default instance
    (``get_registry()``); subsystems may hold private registries (tests
    pass a fresh one into ``ServingEngine`` for isolation)."""

    def __init__(self, enabled: bool = True):
        self._enabled = bool(enabled)
        self._lock = threading.Lock()
        self._instruments: Dict[str, _Instrument] = {}
        # stable in-process identity: consumers that deduplicate
        # SHARED registries (fleet_snapshot, the SLO monitor) key on
        # this instead of id() — a remote replica's registry shim can
        # carry the server registry's key across the wire, where
        # object identity is meaningless (every fetch is a fresh dict)
        self.dedupe_key = f"reg{next(_REGISTRY_UID)}"

    # -- lifecycle --
    def enable(self):
        self._enabled = True

    def disable(self):
        """Freeze every instrument: mutators become one-bool-check
        no-ops (the < 2% decode-loop overhead contract)."""
        self._enabled = False

    @property
    def enabled(self) -> bool:
        return self._enabled

    # -- registration --
    def _register(self, cls, name: str, help_str: str,
                  label_names: Iterable[str], **kw):
        if not NAME_RE.match(name):
            raise ValueError(
                f"invalid instrument name {name!r}: must match "
                f"{NAME_RE.pattern}")
        label_names = tuple(label_names)
        with self._lock:
            existing = self._instruments.get(name)
            if existing is not None:
                if type(existing) is not cls:
                    raise ValueError(
                        f"instrument {name!r} already registered as "
                        f"{existing.kind}, cannot re-register as "
                        f"{cls.kind}")
                if existing.label_names != label_names:
                    raise ValueError(
                        f"instrument {name!r} already registered with "
                        f"labels {existing.label_names}, got {label_names}")
                if cls is Histogram:
                    want = tuple(sorted(float(b)
                                        for b in kw.get("buckets", ())))
                    if want != existing.bounds:
                        raise ValueError(
                            f"histogram {name!r} already registered "
                            f"with buckets {existing.bounds}, got "
                            f"{want} — silently keeping the old bounds "
                            f"would clamp the new site's observations")
                return existing
            inst = cls(self, name, help_str, label_names, **kw)
            self._instruments[name] = inst
            return inst

    def counter(self, name: str, help_str: str = "",
                labels: Iterable[str] = _NO_LABELS) -> Counter:
        return self._register(Counter, name, help_str, labels)

    def gauge(self, name: str, help_str: str = "",
              labels: Iterable[str] = _NO_LABELS) -> Gauge:
        return self._register(Gauge, name, help_str, labels)

    def histogram(self, name: str, help_str: str = "",
                  labels: Iterable[str] = _NO_LABELS,
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._register(Histogram, name, help_str, labels,
                              buckets=buckets)

    def get(self, name: str) -> Optional[_Instrument]:
        with self._lock:
            return self._instruments.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._instruments)

    # -- export --
    def snapshot(self) -> dict:
        """Plain-dict view of every instrument's current values —
        JSON-serializable, suitable for bench deltas via
        ``diff_snapshots``."""
        with self._lock:
            insts = list(self._instruments.values())
        return {inst.name: inst._snap() for inst in insts}

    def to_json(self, indent=None) -> str:
        return json.dumps(self.snapshot(), indent=indent, sort_keys=True)

    def to_prometheus_text(self) -> str:
        """Prometheus exposition format.  Dots become underscores;
        label VALUES are double-quoted and escaped per the exposition
        grammar; histograms emit ``_bucket``/``_sum``/``_count`` series
        plus an interpolated ``<name>_quantile`` GAUGE family (quantile
        as a label) — bare-name ``{quantile=...}`` samples under a
        histogram TYPE would be invalid exposition text and split into
        duplicate unknown families on parse."""
        def plab(lk: str) -> str:
            # snapshot label key "k=v,k2=v2" -> 'k="v",k2="v2"'
            if not lk:
                return ""
            parts = []
            for p in lk.split(","):
                k, _, v = p.partition("=")
                v = (_unesc_label_value(v).replace("\\", "\\\\")
                     .replace('"', '\\"').replace("\n", "\\n"))
                parts.append(f'{k}="{v}"')
            return ",".join(parts)

        lines: List[str] = []
        for name, snap in sorted(self.snapshot().items()):
            pname = name.replace(".", "_")
            if snap["help"]:
                lines.append(f"# HELP {pname} {snap['help']}")
            lines.append(f"# TYPE {pname} {snap['type']}")
            if snap["type"] in ("counter", "gauge"):
                for lk, v in sorted(snap["values"].items()):
                    lines.append(f"{pname}{{{plab(lk)}}} {v}" if lk
                                 else f"{pname} {v}")
            else:  # histogram
                bounds = snap["le"]
                qlines: List[str] = []
                for lk, cell in sorted(snap["values"].items()):
                    lp = plab(lk)
                    prefix = lp + "," if lp else ""
                    cum = 0
                    for b, c in zip(bounds, cell["buckets"]):
                        cum += c
                        lines.append(
                            f'{pname}_bucket{{{prefix}le="{b}"}} {cum}')
                    cum += cell["buckets"][-1]
                    lines.append(
                        f'{pname}_bucket{{{prefix}le="+Inf"}} {cum}')
                    lines.append(f"{pname}_sum{{{lp}}} {cell['sum']}" if lk
                                 else f"{pname}_sum {cell['sum']}")
                    lines.append(f"{pname}_count{{{lp}}} {cell['count']}"
                                 if lk else f"{pname}_count {cell['count']}")
                    for q in ("p50", "p95", "p99"):
                        qv = q[1:] if q != "p50" else "50"
                        qlines.append(
                            f'{pname}_quantile{{{prefix}quantile='
                            f'"0.{qv}"}} {cell[q]}')
                if qlines:
                    lines.append(f"# TYPE {pname}_quantile gauge")
                    lines.extend(qlines)
        return "\n".join(lines) + "\n"


def diff_snapshots(before: dict, after: dict) -> dict:
    """Delta between two ``MetricsRegistry.snapshot()`` dicts: counters
    and histogram buckets subtract (instruments absent from ``before``
    count from zero), gauges keep the ``after`` value (a level has no
    meaningful delta) plus the hwm.  Gauges that moved neither value
    nor hwm inside the window are dropped.  Caveat: ``hwm`` is the
    PROCESS-LIFETIME high-water mark, not a per-window peak — a window
    whose activity stayed below an earlier window's peak reports the
    earlier peak (tracking per-window peaks would need stateful
    watermark resets, which snapshots deliberately avoid).  The shape
    mirrors ``snapshot()`` so the same renderers work on deltas — this
    is what ``bench.py`` embeds per section."""
    out = {}
    for name, snap in after.items():
        prev = before.get(name)
        kind = snap["type"]
        if kind == "counter":
            pv = (prev or {}).get("values", {})
            # zero-delta label cells drop too: a section must not
            # re-report label combinations some earlier section moved
            vals = {k: v - pv.get(k, 0)
                    for k, v in snap["values"].items()
                    if v - pv.get(k, 0)}
            if vals:
                out[name] = {"type": kind, "values": vals}
        elif kind == "gauge":
            # include only gauges that MOVED during the window — a
            # bench section must not re-report levels some earlier
            # section set (value and hwm compared against ``before``)
            pv = (prev or {}).get("values", {})
            ph = (prev or {}).get("hwm", {})
            changed = {
                k: v for k, v in snap["values"].items()
                if pv.get(k) != v or
                ph.get(k) != snap.get("hwm", {}).get(k)}
            if changed:
                out[name] = {"type": kind, "values": changed,
                             "hwm": {k: snap.get("hwm", {}).get(k)
                                     for k in changed}}
        else:  # histogram
            bounds = snap["le"]
            pv = (prev or {}).get("values", {})
            vals = {}
            for lk, cell in snap["values"].items():
                pcell = pv.get(lk)
                counts = list(cell["buckets"])
                count, total = cell["count"], cell["sum"]
                if pcell is not None:
                    counts = [c - p for c, p in zip(counts,
                                                    pcell["buckets"])]
                    count -= pcell["count"]
                    total -= pcell["sum"]
                if count <= 0:
                    continue
                vals[lk] = {
                    "count": count, "sum": total,
                    "p50": _quantile_from_buckets(0.50, bounds, counts),
                    "p95": _quantile_from_buckets(0.95, bounds, counts),
                    "p99": _quantile_from_buckets(0.99, bounds, counts),
                }
            if vals:
                out[name] = {"type": kind, "values": vals}
    return out


_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry every built-in instrument
    records into unless handed a private one."""
    return _default_registry
