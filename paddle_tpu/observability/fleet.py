"""Fleet observability plane: cross-replica trace stitching + the
per-tenant SLO burn-rate monitor.

PR 12/15 made the deployment unit a FLEET — a ``Router`` over N
replicas with failover and exact-bytes migration — but each replica
keeps its own ``FlightRecorder`` and ``MetricsRegistry``, so a request
that fails over mid-flight has its story split across recorders and
there is no windowed view of SLO attainment at all.  This module is
the missing fleet layer, in the repo's deterministic idiom:

- **stitching** (:func:`stitch_flight_records`) — correlates events
  by request id across the router's recorder and every replica's
  recorder into ONE ordered record.  No global clock is needed: the
  router's ``route``/``migrate``/``retry`` events carry the
  destination replica AND the engine-side request id it assigned
  (``rid``), and within one replica's ring each ``submit`` opens a
  new binding generation, so (replica, engine rid, generation) maps
  to exactly one router-global id even when engine ids collide across
  replicas or are reused after ``crash_reset``.  Ordering is by
  ``(step, replica, seq)`` — steps are scheduler iterations, shared
  by construction in the router's lockstep loop, and per-source
  ``seq`` breaks ties deterministically.
- **fleet explain** (:meth:`StitchedRecord.explain`) — narrates the
  full cross-replica journey: "prefilled on engine 0, replica 0
  killed at step 12, migrated 6 blocks to engine 1, finished at
  step 19".
- **one Perfetto file** (:meth:`StitchedRecord.export_chrome_trace`)
  — one process lane per replica (pid = replica index, the router
  lane after them), one thread per router-global request id, through
  the existing ``merge_chrome_traces`` writer.
- **burn-rate monitoring** (:class:`SLOBurnRateMonitor`) — windowed
  SLO attainment per tenant over the existing
  ``serving.slo.attained/missed`` counters, SRE-style burn rate
  (window miss rate over the error budget ``1 - slo_target``),
  lifetime error-budget accounting, and a CLOSED alert vocabulary
  (``ALERT_KINDS``, graftlint-checked).  Alerts are emitted as
  flight-recorder events (kind ``alert``) so they are
  replay-deterministic: same trace, same alert, same step.
- **registry federation** (:func:`merge_registry_snapshots`) — merges
  per-replica ``snapshot()`` dicts into one snapshot-shaped dict with
  a ``replica=<i>`` label prefixed onto every cell, which is what
  ``Router.fleet_snapshot()`` and ``tools/serving_top.py`` render.
"""

from __future__ import annotations

import json
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from .flightrec import (ENGINE_EVENT, FlightEvent, FlightRecord,
                        FlightRecorder, _plural, events_from_record,
                        load_flight_record)
from .metrics import (MetricsRegistry, _esc_label_value,
                      _unesc_label_value, get_registry)

# the closed vocabulary of fleet alerts (graftlint's vocab pass keeps
# it closed AND alive — every entry has a literal
# ``alerts.inc(kind=...)`` site in SLOBurnRateMonitor.observe):
# burn_rate          windowed SLO miss rate crossed the burn threshold
#                    for one tenant (attrs: tenant, burn)
# budget_exhausted   a tenant's lifetime misses consumed its whole
#                    error budget (attrs: tenant, missed, total)
# replica_unhealthy  a replica left the routing set (attrs: engine)
# queue_saturation   the router-held queue reached its saturation
#                    depth (attrs: depth, threshold)
ALERT_KINDS = ("burn_rate", "budget_exhausted", "replica_unhealthy",
               "queue_saturation")

# the router's process lane label in stitched records (engine events
# carry their integer replica index)
ROUTER_LANE = "router"


def orphan_id(replica: int, rid: int) -> int:
    """Deterministic synthetic global id for an engine-local request
    no router binding claims (health probes submitted directly to the
    replica): distinct from every router id (>= 0) and from
    ``ENGINE_EVENT`` (-1), unique per (replica, rid)."""
    return -(1000 + 1000 * int(replica) + int(rid))


@dataclass
class StitchedEvent(FlightEvent):
    """One stitched event: a :class:`FlightEvent` whose ``request`` is
    the router-GLOBAL id, annotated with the source lane (``replica``:
    int replica index, or ``"router"``) and the id the source record
    used (``source_request`` — the per-engine rid, which may collide
    across replicas; the stitcher's whole job is resolving it)."""
    replica: object = None
    source_request: int = 0

    def as_dict(self) -> dict:
        d = super().as_dict()
        d["replica"] = self.replica
        d["source_request"] = self.source_request
        return d


def _load_source(src) -> Tuple[List[FlightEvent], int]:
    """Normalize one stitch input to ``(events, dropped)``.  Accepts a
    live :class:`FlightRecorder`, an export path, a parsed export
    dict, or an event list (a :class:`FlightRecord` carries its own
    drop count; a bare list counts as complete)."""
    if isinstance(src, FlightRecorder):
        return src.events(), src.dropped
    if isinstance(src, str):
        rec = load_flight_record(src)
        return list(rec), rec.dropped
    if isinstance(src, dict):
        return events_from_record(src), int(src.get("dropped", 0))
    return list(src), int(getattr(src, "dropped", 0))


def stitch_flight_records(records: Sequence, *,
                          router=None) -> "StitchedRecord":
    """Correlate per-replica flight records (list index = replica
    index) and the router's record into one :class:`StitchedRecord`.

    With a ``router`` record, engine events are re-keyed to
    router-global ids via the binding map its ``route`` / ``migrate``
    / ``retry`` events carry (``engine=`` + ``rid=`` attrs), FIFO per
    (replica, rid) across submit generations; engine requests no
    binding claims (direct submissions such as health probes) get
    :func:`orphan_id`.  Without one, engine ids pass through verbatim
    — exact for a single replica, ambiguous across several (the
    caller was warned).  Events keep their source ``step``/``seq``
    and order by ``(step, lane, seq)``, router lane first within a
    step (the router routes before it steps its engines)."""
    srcs = [_load_source(r) for r in records]
    router_events: Optional[List[FlightEvent]] = None
    dropped: Dict[str, int] = {}
    if router is not None:
        router_events, rdrop = _load_source(router)
        dropped[ROUTER_LANE] = rdrop
    for i, (_evs, drop) in enumerate(srcs):
        dropped[str(i)] = drop

    # (replica, engine rid) -> router ids, in router emission order:
    # the k-th binding of a pair serves that pair's k-th submit
    # generation on the replica
    bindings: Dict[Tuple[int, int], List[int]] = {}
    if router_events is not None:
        for e in sorted(router_events, key=lambda e: e.seq):
            if e.kind not in ("route", "migrate", "retry",
                              "handoff"):
                continue
            ei, rid = e.attrs.get("engine"), e.attrs.get("rid")
            if ei is None or rid is None:
                continue
            bindings.setdefault((int(ei), int(rid)), []) \
                .append(e.request)

    out: List[StitchedEvent] = []
    if router_events is not None:
        for e in router_events:
            out.append(StitchedEvent(
                e.seq, e.step, e.request, e.kind, e.wall,
                dict(e.attrs), ROUTER_LANE, e.request))
    for i, (evs, _drop) in enumerate(srcs):
        gen: Dict[int, int] = {}
        for e in sorted(evs, key=lambda e: e.seq):
            if e.request == ENGINE_EVENT:
                gid = ENGINE_EVENT
            elif router_events is None:
                gid = e.request
            else:
                if e.kind == "submit":
                    gen[e.request] = gen.get(e.request, -1) + 1
                g = gen.get(e.request, 0)
                blist = bindings.get((i, e.request), [])
                gid = (blist[g] if g < len(blist)
                       else orphan_id(i, e.request))
            out.append(StitchedEvent(
                e.seq, e.step, gid, e.kind, e.wall, dict(e.attrs),
                i, e.request))

    def lane_rank(e: StitchedEvent) -> int:
        return -1 if e.replica == ROUTER_LANE else int(e.replica)

    out.sort(key=lambda e: (e.step, lane_rank(e), e.seq))
    return StitchedRecord(out, replicas=len(srcs), dropped=dropped)


class StitchedRecord:
    """The stitched fleet record: one ordered event list spanning the
    router and every replica, keyed by router-global request ids."""

    def __init__(self, events: List[StitchedEvent], *, replicas: int,
                 dropped: Optional[Dict[str, int]] = None):
        self.events = list(events)
        self.replicas = int(replicas)
        self.dropped = dict(dropped or {})

    @property
    def dropped_total(self) -> int:
        return sum(self.dropped.values())

    def __len__(self) -> int:
        return len(self.events)

    def request_ids(self) -> List[int]:
        """Router-global ids (orphans and engine-scoped lanes
        excluded)."""
        return sorted({e.request for e in self.events
                       if e.request >= 0})

    def timeline(self, request_id: int) -> List[StitchedEvent]:
        return [e for e in self.events if e.request == request_id]

    # -- narration --
    def explain(self, request_id: int) -> str:
        """The request's full cross-replica journey as one sentence —
        every engine-side clause names its replica, failover hops
        name source and destination, and a ring that dropped events
        anywhere in the fleet is called out (the story may have
        holes)."""
        tl = self.timeline(request_id)
        if not tl:
            note = (f"; the fleet's rings dropped "
                    f"{_plural(self.dropped_total, 'event')}"
                    if self.dropped_total else "")
            return (f"request {request_id}: no events in the stitched "
                    f"record (wrong id, or the rings dropped them)"
                    + note)
        parts: List[str] = []
        # per-replica-segment accumulators (chunks/blocks/verifies are
        # per-dispatch events — a sentence per dispatch would bury the
        # journey, so they aggregate until the story changes lanes)
        seg_rep: object = None
        chunks = blocks = accepted = rejected = verifies = 0

        def flush():
            nonlocal chunks, blocks, accepted, rejected, verifies
            if chunks:
                parts.append(f"prefilled in {_plural(chunks, 'chunk')} "
                             f"on engine {seg_rep}")
                chunks = 0
            if verifies:
                parts.append(
                    f"{_plural(accepted, 'spec position')} accepted / "
                    f"{rejected} rejected over "
                    f"{_plural(verifies, 'verify forward')} on engine "
                    f"{seg_rep}")
                accepted = rejected = verifies = 0
            if blocks:
                parts.append(f"rode {_plural(blocks, 'decode block')} "
                             f"on engine {seg_rep}")
                blocks = 0

        has_router = any(e.replica == ROUTER_LANE for e in tl)
        for e in tl:
            rep, k, a = e.replica, e.kind, e.attrs
            if rep != ROUTER_LANE and rep != seg_rep:
                flush()
                seg_rep = rep
            if k == "prefill_chunk":
                chunks += 1
                continue
            if k == "decode_block":
                blocks += 1
                continue
            if k == "spec_verify":
                verifies += 1
                accepted += int(a.get("accepted", 0))
                rejected += int(a.get("rejected", 0))
                continue
            flush()
            if k == "submit":
                if rep == ROUTER_LANE:
                    parts.append(f"submitted at step {e.step}")
                elif not has_router:
                    parts.append(f"submitted at step {e.step} on "
                                 f"engine {rep}")
                # engine-side submit after a router submit is the
                # dispatch itself — the route clause already tells it
            elif k == "route":
                clause = f"routed to engine {a.get('engine', '?')}"
                det = []
                if int(a.get("affinity", 0)):
                    det.append(f"prefix affinity {a['affinity']} "
                               f"tokens")
                if a.get("adapter_hit"):
                    det.append("adapter resident")
                if "reason" in a:
                    det.append(f"by {a['reason']}")
                if det:
                    clause += " (" + ", ".join(det) + ")"
                parts.append(clause)
            elif k == "admit":
                parts.append(f"admitted on engine {rep} at step "
                             f"{e.step} into slot {a.get('slot', '?')}")
            elif k == "prefix_hit":
                parts.append(
                    f"prefix hit ({a.get('tier', '?')}) on engine "
                    f"{rep}: "
                    f"{_plural(int(a.get('blocks', 0)), 'cached block')}"
                    f" mapped at step {e.step}")
            elif k == "preempt":
                parts.append(
                    f"preempted on engine {rep} at step {e.step} "
                    f"({_plural(int(a.get('blocks', 0)), 'block')} to "
                    f"host)")
            elif k == "swap_in":
                parts.append(
                    f"resumed on engine {rep} at step {e.step} via "
                    f"{_plural(int(a.get('blocks', 0)), 'host block')}")
            elif k == "fail":
                if a.get("terminal"):
                    nr = int(a.get("retries", 0))
                    parts.append(
                        f"failed terminally at step {e.step} (retry "
                        f"budget exhausted after {nr} "
                        f"{'retry' if nr == 1 else 'retries'})")
                elif a.get("fault") == "kill":
                    parts.append(f"replica {a.get('engine', '?')} "
                                 f"killed at step {e.step}")
                else:
                    parts.append(
                        f"replica {a.get('engine', '?')} failed under "
                        f"{a.get('fault', '?')} at step {e.step}")
            elif k == "migrate":
                parts.append(
                    f"migrated "
                    f"{_plural(int(a.get('blocks', 0)), 'block')} to "
                    f"engine {a.get('engine', '?')} at exact bytes")
            elif k == "retry":
                how = ("recomputed from prompt"
                       if a.get("path") == "recompute" else "re-queued")
                parts.append(
                    f"failed over to engine {a.get('engine', '?')} "
                    f"({how}, attempt {a.get('attempt', '?')})")
            elif k == "handoff":
                if rep == ROUTER_LANE:
                    parts.append(
                        f"prefilled on engine {a.get('src', '?')}, "
                        f"handed off "
                        f"{_plural(int(a.get('blocks', 0)), 'block')} "
                        f"to engine {a.get('engine', '?')} at "
                        f"chunk-final")
                elif not has_router:
                    parts.append(
                        f"handed off "
                        f"{_plural(int(a.get('blocks', 0)), 'block')} "
                        f"at chunk-final from engine {rep}")
                # engine-side handoff after a router handoff is the
                # same hop — the router clause names both endpoints
            elif k == "finish":
                extra = (f" after {_plural(int(a['tokens']), 'token')}"
                         if "tokens" in a else "")
                where = (f" on engine {rep}" if rep != ROUTER_LANE
                         else "")
                parts.append(f"finished at step {e.step}{extra}{where}")
            elif k == "alert":
                parts.append(f"alert {a.get('kind', '?')} at step "
                             f"{e.step}")
            elif k in ("timeout", "shed", "cancel"):
                verb = {"timeout": "timed out", "shed": "shed",
                        "cancel": "cancelled"}[k]
                parts.append(f"{verb} at step {e.step}")
        flush()
        text = f"request {request_id}: " + "; ".join(parts)
        if self.dropped_total:
            worst = ", ".join(
                f"{'router' if k == ROUTER_LANE else 'replica ' + k}: "
                f"{v}" for k, v in sorted(self.dropped.items()) if v)
            text += (f" [rings dropped "
                     f"{_plural(self.dropped_total, 'event')} "
                     f"({worst}) — the story may have holes]")
        return text

    # -- export --
    def to_dict(self, *, drop_wall: bool = False) -> dict:
        """JSON-ready form.  ``drop_wall=True`` zeroes the report-only
        wall stamps — the canonical form two replays of one trace
        agree on byte for byte."""
        evs = []
        for e in self.events:
            d = e.as_dict()
            if drop_wall:
                d["wall"] = 0.0
            evs.append(d)
        return {"version": 1, "replicas": self.replicas,
                "dropped": dict(sorted(self.dropped.items())),
                "n_events": len(self.events), "events": evs}

    def export(self, path: str) -> dict:
        d = self.to_dict()
        with open(path, "w") as f:
            json.dump(d, f, sort_keys=True)
        return {"version": 1, "replicas": self.replicas,
                "n_events": len(self.events),
                "dropped": dict(self.dropped)}

    def chrome_events(self) -> list:
        """The stitched record as chrome event dicts: one PROCESS lane
        per replica (pid = replica index; the router lane rides
        pid = ``replicas``), one thread per router-global request id,
        instants named ``flightrec.<kind>`` with attrs in ``args`` —
        ready for ``merge_chrome_traces(out, host=[], extra=...)``."""
        out = []
        for pid in range(self.replicas):
            out.append({"ph": "M", "pid": pid, "name": "process_name",
                        "args": {"name": f"replica {pid}"}})
        rpid = self.replicas
        out.append({"ph": "M", "pid": rpid, "name": "process_name",
                    "args": {"name": "router"}})
        for e in self.events:
            pid = rpid if e.replica == ROUTER_LANE else int(e.replica)
            out.append({
                "name": f"flightrec.{e.kind}", "ph": "i", "s": "t",
                "pid": pid, "tid": e.request, "ts": e.wall * 1e6,
                "args": {"request": e.request, "step": e.step,
                         "source_request": e.source_request,
                         **e.attrs}})
        return out

    def export_chrome_trace(self, out_path: str,
                            device_trace_dir: Optional[str] = None
                            ) -> dict:
        """One-call Perfetto export through the existing
        ``merge_chrome_traces`` writer (replica lanes via its
        ``extra=`` hook; a host pid-0 metadata line precedes replica
        0's — Perfetto keeps the last process_name, so the lane reads
        "replica 0")."""
        from .spans import merge_chrome_traces
        return merge_chrome_traces(out_path, host=[],
                                   device_trace_dir=device_trace_dir,
                                   extra=self.chrome_events())


# ---------------------------------------------------------------------------
# registry federation
# ---------------------------------------------------------------------------

def merge_registry_snapshots(snaps: Sequence, *,
                             label: str = "replica") -> dict:
    """Merge per-replica ``MetricsRegistry.snapshot()`` dicts into one
    snapshot-shaped dict, prefixing ``label=<value>`` onto every label
    key (the Prometheus-federation idiom: same series, one extra
    label).  ``snaps`` is a sequence of snapshots (values = list
    indices) or of ``(value, snapshot)`` pairs.  Instruments whose
    kind disagrees across snapshots raise — replicas are homogeneous
    by construction, so a disagreement is a bug, not data."""
    pairs = []
    for i, s in enumerate(snaps):
        if isinstance(s, tuple):
            pairs.append((str(s[0]), s[1]))
        else:
            pairs.append((str(i), s))
    out: dict = {}
    for val, snap in pairs:
        prefix = f"{label}={_esc_label_value(val)}"
        for name, inst in snap.items():
            tgt = out.get(name)
            if tgt is None:
                tgt = {"type": inst["type"], "help": inst.get("help", ""),
                       "labels": [label] + list(inst.get("labels", ())),
                       "values": {}}
                if inst["type"] == "gauge":
                    tgt["hwm"] = {}
                if inst["type"] == "histogram":
                    tgt["le"] = list(inst.get("le", ()))
                out[name] = tgt
            elif tgt["type"] != inst["type"]:
                raise ValueError(
                    f"instrument {name!r} is a {inst['type']} in "
                    f"{label}={val} but a {tgt['type']} in an earlier "
                    f"snapshot — replicas must be homogeneous")
            for lk, v in inst.get("values", {}).items():
                key = prefix + ("," + lk if lk else "")
                tgt["values"][key] = v
            for lk, v in inst.get("hwm", {}).items():
                key = prefix + ("," + lk if lk else "")
                tgt.setdefault("hwm", {})[key] = v
    return out


def _label_value(label_key: str, name: str) -> Optional[str]:
    """The ``name`` label's value out of a snapshot label key
    (``"class=p1,tenant=a"``), unescaped; None when absent."""
    for part in label_key.split(","):
        k, _, v = part.partition("=")
        if k == name:
            return _unesc_label_value(v)
    return None


# ---------------------------------------------------------------------------
# SLO burn-rate monitor
# ---------------------------------------------------------------------------

class _MonitorInstruments:
    """Registry handles for the monitor's observable surface."""

    def __init__(self, registry: MetricsRegistry):
        self.registry = registry
        r = registry
        self.burn_rate = r.gauge(
            "serving.slo.burn_rate",
            "windowed SLO burn rate per tenant: the window's miss "
            "rate over the error budget (1 - slo_target); 1.0 burns "
            "the budget exactly at the sustainable rate, above it the "
            "budget drains early (SRE burn-rate alerting over the "
            "serving.slo.attained/missed counters)",
            labels=("tenant",))
        self.alerts = r.counter(
            "serving.alerts",
            "fleet monitor alerts fired, by closed kind vocabulary "
            "(ALERT_KINDS: burn_rate / budget_exhausted / "
            "replica_unhealthy / queue_saturation); each firing also "
            "rides the flight recorder as an 'alert' event, so alerts "
            "are replay-deterministic",
            labels=("kind",))
        self.monitor_steps = r.counter(
            "serving.fleet.monitor_steps",
            "SLOBurnRateMonitor.observe() calls (one per router step "
            "when attached via Router(monitor=...)) — the monitoring "
            "plane's own liveness signal")


class SLOBurnRateMonitor:
    """Windowed per-tenant SLO attainment + closed-vocabulary alerts.

    Reads the per-replica ``serving.slo.attained/missed{class,tenant}``
    counters (summed over classes and deduplicated registries), keeps
    a bounded ring of per-step totals, and fires ``ALERT_KINDS``
    alerts — each alert increments ``serving.alerts{kind}`` AND rides
    the flight recorder as an ``alert`` event, so a replayed trace
    fires the same alert at the same step.  Alerts LATCH: a condition
    fires once on crossing and re-arms only after it clears, so one
    sustained incident is one alert, not one per step.

    Drive it directly (``observe(...)`` once per scheduler step) or
    attach it to a router (``Router(monitor=...)``), which binds the
    router's registry/recorder as defaults and observes at the end of
    every ``router.step()``.
    """

    def __init__(self, *, slo_target: float = 0.99,
                 window_steps: int = 32,
                 burn_threshold: float = 1.0,
                 queue_saturation_depth: Optional[int] = None,
                 registry: Optional[MetricsRegistry] = None,
                 flight_recorder: Optional[FlightRecorder] = None):
        if not 0.0 < slo_target < 1.0:
            raise ValueError(
                f"slo_target must be in (0, 1), got {slo_target}")
        if window_steps < 2:
            raise ValueError(
                f"window_steps must be >= 2, got {window_steps}")
        if burn_threshold <= 0:
            raise ValueError(
                f"burn_threshold must be > 0, got {burn_threshold}")
        self.slo_target = float(slo_target)
        self.window_steps = int(window_steps)
        self.burn_threshold = float(burn_threshold)
        self.queue_saturation_depth = (
            None if queue_saturation_depth is None
            else int(queue_saturation_depth))
        self._registry = registry
        self._fr = flight_recorder
        self._m: Optional[_MonitorInstruments] = None
        self._ring: deque = None  # created on first observe
        self._alerts: List[dict] = []
        self._latched: set = set()   # (kind, key) pairs currently firing
        self._prev_health: List[str] = []
        if registry is not None:
            self._m = _MonitorInstruments(registry)

    # -- binding (Router(monitor=...) calls this) --
    def _bind(self, registry: MetricsRegistry,
              flight_recorder: FlightRecorder):
        """Adopt the router's registry/recorder UNLESS explicitly
        constructed with our own (the FlightRecorder.bind_clock
        discipline)."""
        if self._registry is None:
            self._registry = registry
        if self._fr is None:
            self._fr = flight_recorder
        if self._m is None:
            self._m = _MonitorInstruments(self._registry)

    def _instruments(self) -> _MonitorInstruments:
        if self._m is None:
            if self._registry is None:
                self._registry = get_registry()
            self._m = _MonitorInstruments(self._registry)
        return self._m

    # -- observation --
    def _tenant_totals(self, registries) -> Dict[str, List[int]]:
        """{tenant: [attained, missed]} summed over classes and the
        DEDUPLICATED registry set (replicas may share one registry —
        summing it per replica would multiply every outcome)."""
        seen = set()
        out: Dict[str, List[int]] = {}
        for reg in registries:
            # dedupe by the registry's STABLE key when it has one —
            # remote-replica registry shims are fresh objects per
            # fetch, so id() would double-count one shared server
            # registry (PR 19); id() remains the bare-object fallback
            k = getattr(reg, "dedupe_key", None) or id(reg)
            if reg is None or k in seen:
                continue
            seen.add(k)
            for name, slot in (("serving.slo.attained", 0),
                               ("serving.slo.missed", 1)):
                inst = reg.get(name)
                if inst is None:
                    continue
                for lk, v in inst._snap()["values"].items():
                    tenant = _label_value(lk, "tenant") or "default"
                    out.setdefault(tenant, [0, 0])[slot] += int(v)
        return out

    def _fire(self, kind: str, step: int, **attrs):
        self._alerts.append({"kind": kind, "step": int(step), **attrs})
        if self._fr is not None:
            self._fr.emit("alert", ENGINE_EVENT, step, kind=kind,
                          **attrs)

    def observe(self, *, step: int, registries: Sequence = (),
                health: Sequence[str] = (),
                queue_depth: int = 0,
                max_queue: Optional[int] = None):
        """One monitoring tick.  Deterministic: reads only counters
        and the passed scheduler state, never the clock."""
        m = self._instruments()
        m.monitor_steps.inc()
        if self._ring is None:
            self._ring = deque(maxlen=self.window_steps)
        totals = self._tenant_totals(registries)
        self._ring.append({"step": int(step), "tenants": {
            t: list(v) for t, v in totals.items()}})
        base = self._ring[0]["tenants"]
        budget_frac = 1.0 - self.slo_target
        for tenant in sorted(totals):
            att, miss = totals[tenant]
            batt, bmiss = base.get(tenant, (0, 0))
            datt, dmiss = att - batt, miss - bmiss
            denom = datt + dmiss
            burn = ((dmiss / denom) / budget_frac) if denom else 0.0
            m.burn_rate.set(burn, tenant=tenant)
            key = ("burn_rate", tenant)
            if burn >= self.burn_threshold:
                if key not in self._latched:
                    self._latched.add(key)
                    m.alerts.inc(kind="burn_rate")
                    self._fire("burn_rate", step, tenant=tenant,
                               burn=round(burn, 6))
            else:
                self._latched.discard(key)
            total = att + miss
            key = ("budget_exhausted", tenant)
            if total and miss > budget_frac * total:
                if key not in self._latched:
                    self._latched.add(key)
                    m.alerts.inc(kind="budget_exhausted")
                    self._fire("budget_exhausted", step, tenant=tenant,
                               missed=miss, total=total)
            else:
                self._latched.discard(key)
        for i, state in enumerate(health):
            key = ("replica_unhealthy", i)
            if state == "unhealthy":
                if key not in self._latched:
                    self._latched.add(key)
                    m.alerts.inc(kind="replica_unhealthy")
                    self._fire("replica_unhealthy", step, engine=i)
            else:
                self._latched.discard(key)
        self._prev_health = list(health)
        threshold = (self.queue_saturation_depth
                     if self.queue_saturation_depth is not None
                     else max_queue)
        key = ("queue_saturation", "")
        if threshold is not None and queue_depth >= threshold:
            if key not in self._latched:
                self._latched.add(key)
                m.alerts.inc(kind="queue_saturation")
                self._fire("queue_saturation", step,
                           depth=int(queue_depth),
                           threshold=int(threshold))
        else:
            self._latched.discard(key)

    # -- queries --
    def alerts(self) -> List[dict]:
        """Every alert fired so far (kind, step, context attrs), in
        firing order — deterministic across replays."""
        return list(self._alerts)

    def burn_rates(self) -> Dict[str, float]:
        """Current windowed burn rate per tenant."""
        if not self._ring:
            return {}
        newest, base = self._ring[-1]["tenants"], self._ring[0]["tenants"]
        out = {}
        for t, (att, miss) in sorted(newest.items()):
            batt, bmiss = base.get(t, (0, 0))
            denom = (att - batt) + (miss - bmiss)
            out[t] = (((miss - bmiss) / denom) / (1.0 - self.slo_target)
                      if denom else 0.0)
        return out

    def budgets(self) -> Dict[str, dict]:
        """Lifetime error-budget accounting per tenant: the budget is
        ``(1 - slo_target)`` of all SLO-carrying outcomes; consumed
        is the missed fraction of it (>= 1.0 = exhausted)."""
        if not self._ring:
            return {}
        out = {}
        frac = 1.0 - self.slo_target
        for t, (att, miss) in sorted(self._ring[-1]["tenants"].items()):
            total = att + miss
            budget = frac * total
            out[t] = {"attained": att, "missed": miss, "total": total,
                      "budget": budget,
                      "consumed": (miss / budget) if budget else 0.0}
        return out

    def summary(self) -> dict:
        """The snapshot-ready view ``Router.fleet_snapshot()``
        embeds."""
        by_kind: Dict[str, int] = {}
        for a in self._alerts:
            by_kind[a["kind"]] = by_kind.get(a["kind"], 0) + 1
        return {"slo_target": self.slo_target,
                "window_steps": self.window_steps,
                "burn_threshold": self.burn_threshold,
                "burn_rate": self.burn_rates(),
                "budget": self.budgets(),
                "alerts": list(self._alerts),
                "alerts_by_kind": by_kind}
