"""Structured spans over the host tracer + host/device trace merging.

``span(name, **attrs)`` is the structured face of
``runtime.HostTracer``: a range on the calling thread's lane whose
attributes are encoded into the event name (the tracer's native event
tuple has no args field — native C++ and Python fallback share the
``(kind, t0, t1, tid, value, name)`` schema), using ``;k=v`` suffixes
that ``parse_span_name`` and the chrome-trace merger decode back into
Perfetto ``args``.  When the tracer is disabled ``__enter__`` is one
attribute load + bool test — attrs are never formatted — so
instrumented hot loops (the serving scheduler) pay nothing outside a
profiling window.

``merge_chrome_traces`` stitches the host chrome trace and the
``jax.profiler`` device dump (the ``*.trace.json.gz`` files
``DeviceSummaryView._load`` reads) into ONE Perfetto-loadable JSON:
host lanes keep pid 0, device processes are re-numbered into a disjoint
pid range, and metadata (process/thread names) is preserved.  The two
clock domains are not re-aligned — Perfetto shows them as separate
process groups, which is what correlating "queue stall here, device
idle there" needs in practice.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
from typing import Optional

from .. import runtime as rt

_ATTR_SEP = ";"

# tracing-window generation: bumped by Profiler at every record-window
# start (after HostTracer.clear()).  Ranges opened in an earlier window
# no longer exist on the tracer, so a close crossing a window boundary
# must become a no-op instead of popping an unrelated range.
_trace_gen = 0


def current_trace_generation() -> int:
    return _trace_gen


def bump_trace_generation() -> int:
    global _trace_gen
    _trace_gen += 1
    return _trace_gen


def _esc_attr(v) -> str:
    """Escape ``;``/``=`` in attr values so a value cannot fabricate
    extra attrs on re-parse (same contract as the metrics label-key
    escaping; inverse is ``_unesc_attr``)."""
    return (str(v).replace("%", "%25").replace(";", "%3B")
            .replace("=", "%3D"))


def _unesc_attr(v: str) -> str:
    return v.replace("%3D", "=").replace("%3B", ";").replace("%25", "%")


def format_span_name(name: str, attrs: dict) -> str:
    if not attrs:
        return name
    return name + _ATTR_SEP + _ATTR_SEP.join(
        f"{k}={_esc_attr(v)}" for k, v in attrs.items())


def parse_span_name(encoded: str):
    """Inverse of ``format_span_name``: ``(name, attrs_dict)``."""
    if _ATTR_SEP not in encoded:
        return encoded, {}
    name, *parts = encoded.split(_ATTR_SEP)
    attrs = {}
    for p in parts:
        k, _, v = p.partition("=")
        if k:
            attrs[k] = _unesc_attr(v)
    return name, attrs


class span:
    """Context manager recording a named host range with attributes.

    with span("serving.decode_block", steps=4, active=7):
        run_block()

    Re-entrant per instance is NOT supported (one range per ``with``);
    nesting distinct spans is (the tracer keeps a per-thread stack).
    """

    __slots__ = ("_name", "_attrs", "_active", "_gen")

    def __init__(self, name: str, **attrs):
        self._name = name
        self._attrs = attrs
        self._active = False
        self._gen = 0

    def __enter__(self):
        if rt.HostTracer.enabled:
            self._active = True
            self._gen = _trace_gen
            rt.HostTracer.begin(format_span_name(self._name, self._attrs))
        return self

    def __exit__(self, *exc):
        if self._active:
            self._active = False
            # a window boundary between enter and exit invalidated the
            # opened range — closing now would pop someone else's
            if self._gen == _trace_gen:
                rt.HostTracer.end()
        return False


def instant(name: str, **attrs):
    """Zero-duration marker (request queued / finished) with attrs."""
    if rt.HostTracer.enabled:
        rt.HostTracer.instant(format_span_name(name, attrs))


def _host_events_as_chrome(events) -> list:
    """HostTracer event tuples -> chrome trace events, span-attr names
    decoded into ``args``."""
    out = []
    for kind, t0, t1, tid, value, raw in events:
        name, attrs = parse_span_name(raw)
        e = {"name": name, "pid": 0, "tid": tid, "ts": t0 / 1e3}
        if attrs:
            e["args"] = attrs
        if kind == 0:
            e.update(ph="X", dur=(t1 - t0) / 1e3)
        elif kind == 1:
            e.update(ph="i", s="t")
        else:
            e.update(ph="C", args={"value": value, **attrs})
        out.append(e)
    return out


def merge_chrome_traces(out_path: str, host=None,
                        device_trace_dir: Optional[str] = None,
                        extra=None) -> dict:
    """Write one chrome/Perfetto JSON combining host spans and the
    jax.profiler device capture.

    ``host``: path to an exported host chrome trace, a list of
    HostTracer event tuples, or None (= the live tracer buffer).
    ``device_trace_dir``: the ``Profiler.device_trace_dir`` /
    ``jax.profiler.start_trace`` directory; None or a dir without
    captures yields a host-only trace (still valid JSON).
    ``extra``: already-formed chrome event dicts appended verbatim —
    the hook fleet exports use to add one process lane per replica
    (their own pids + process_name metadata) without re-implementing
    the writer; callers own pid disjointness from the device range
    (>= 1000).

    Returns summary counts: ``{"host_events", "device_events",
    "device_processes", "extra_events", "path"}``.
    """
    events = [{"ph": "M", "pid": 0, "name": "process_name",
               "args": {"name": "host (paddle_tpu.runtime.HostTracer)"}}]
    if host is None:
        host_events = _host_events_as_chrome(rt.HostTracer.events())
    elif isinstance(host, str):
        with open(host) as f:
            host_events = json.load(f).get("traceEvents", [])
        # an exported host trace carries raw encoded names — decode the
        # span-attr suffixes here too, so all three input forms honor
        # the "attrs land as Perfetto args" contract
        for e in host_events:
            raw = e.get("name", "")
            if _ATTR_SEP in raw:
                e["name"], attrs = parse_span_name(raw)
                if attrs:
                    e["args"] = {**attrs, **e.get("args", {})}
    else:
        host_events = _host_events_as_chrome(host)
    events.extend(host_events)
    n_extra = 0
    if extra is not None:
        for e in extra:
            events.append(e)
            if e.get("ph") != "M":
                n_extra += 1

    n_dev = 0
    pid_map = {}
    if device_trace_dir:
        # device pids are renumbered from 1000 upward per (file, pid) so
        # multiple capture files cannot collide with each other or host
        for path in sorted(glob.glob(os.path.join(
                device_trace_dir, "**", "*.trace.json.gz"),
                recursive=True)):
            with gzip.open(path, "rt") as f:
                raw = json.load(f).get("traceEvents", [])
            for e in raw:
                pid = e.get("pid")
                if pid is None:
                    continue
                key = (path, pid)
                if key not in pid_map:
                    pid_map[key] = 1000 + len(pid_map)
                e = dict(e)
                e["pid"] = pid_map[key]
                events.append(e)
                if e.get("ph") != "M":
                    n_dev += 1
    with open(out_path, "w") as f:
        json.dump({"traceEvents": events,
                   "displayTimeUnit": "ms"}, f)
    return {"host_events": len(host_events), "device_events": n_dev,
            "device_processes": len(pid_map), "extra_events": n_extra,
            "path": out_path}
