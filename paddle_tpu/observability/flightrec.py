"""Per-request flight recorder: a bounded ring of structured lifecycle
events answering "why was THIS request slow".

The metrics registry (PR 2) aggregates — when one request's TTFT blows
up it can say the fleet preempted 14 times, but not that *this*
request waited 3 steps behind request 7, was preempted at step 12 and
resumed via 6 host-RAM blocks.  The flight recorder keeps that
per-request story: every ``ServingEngine`` lifecycle transition emits
one structured event (kind + request id + scheduler step + attrs) into
a bounded ring buffer; ``timeline()`` filters one request's events,
``explain()`` renders them as one human-readable sentence, and
``chrome_events()`` re-encodes the ring as HostTracer-style event
tuples (one lane per request) that ``merge_chrome_traces`` stitches
into the same Perfetto file as the host spans and the device dump.

Design constraints (mirrors ``observability.metrics``):

- **near-zero cost when disabled** — ``emit()`` starts with one
  attribute load + bool test; kind validation, timestamping and the
  ring append happen only on the enabled path (mislabeled kinds
  surface on enable, the ``_resolve_labels`` argument).
- **bounded** — the ring is a ``deque(maxlen=capacity)``: overflow
  drops the OLDEST events (the newest tail is what an incident
  investigation needs) and ``dropped`` counts the loss so an export
  is never silently partial.
- **deterministic modulo wall time** — every field except ``wall`` is
  derived from scheduler state, never from the clock, so two replays
  of one trace produce identical event sequences (the determinism
  contract tests assert; attrs must never carry wall-derived values).

The export format (``export()``/``load_flight_record``) is plain JSON
so ``tools/explain_request.py`` can post-mortem a record from another
process with no framework import beyond this module.
"""

from __future__ import annotations

import json
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .spans import format_span_name

# the closed vocabulary of lifecycle transitions a ServingEngine emits;
# emit() rejects anything else so a typo'd kind cannot silently create
# a parallel event stream no consumer (explain, the CLI) knows about
EVENT_KINDS = frozenset({
    "submit",         # accepted into the queue
    "route",          # router chose an engine replica (engine, affinity,
    #                   policy — emitted by Router, not the engine)
    "admit",          # queue -> slot (prefill starts after mapped blocks)
    "prefix_hit",     # admission mapped cached blocks (tier=hbm|host|partial)
    "prefill_chunk",  # one chunked-prefill dispatch for this request
    "decode_block",   # this request rode one decode-block dispatch
    "spec_verify",    # one verify forward's accept/reject outcome (per slot)
    "preempt",        # swapped out to the host-RAM tier mid-flight
    "swap_out",       # KV blocks left HBM (reason=preempt|cache)
    "swap_in",        # KV blocks re-entered HBM (reason=preempt|cache)
    "shed",           # displaced from a full bounded queue
    "timeout",        # queue wait exceeded max_queue_delay_s
    "cancel",         # dropped by cancel() (attrs carry the phase)
    "finish",         # retired normally (EOS or budget)
    "fail",           # the request's replica failed (attrs: engine,
    #                   fault=kill|poison|stall; terminal=1 + retries
    #                   when the retry budget ran out -> state failed)
    "migrate",        # exact-bytes KV migration to a healthy replica
    #                   (attrs: engine=dest, src, blocks)
    "retry",          # re-placed on a healthy replica (attrs:
    #                   engine=dest, path=recompute|requeue, attempt)
    "handoff",        # disaggregated chunk-final handoff: prefill
    #                   replica -> decode replica through the router
    #                   stage (router event attrs: engine=dest, src,
    #                   blocks, rid; engine event attrs: blocks,
    #                   reason — same parcel, two vantage points)
    "alert",          # fleet monitor alarm (observability.fleet
    #                   SLOBurnRateMonitor): attrs carry kind
    #                   (ALERT_KINDS) + deterministic context; request
    #                   is ENGINE_EVENT — an alert is fleet-scoped, and
    #                   riding the recorder makes it replay-deterministic
})

# request id recorded for engine-scoped events (prefix-cache demotions
# happen on behalf of the POOL, not of one request)
ENGINE_EVENT = -1


@dataclass
class FlightEvent:
    """One lifecycle event.  ``seq`` is the recorder-global monotonic
    index (total order of emission), ``step`` the engine scheduler
    iteration it happened in, ``wall`` the recorder clock at emission —
    the ONE field excluded from determinism comparisons."""
    seq: int
    step: int
    request: int
    kind: str
    wall: float
    attrs: Dict = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {"seq": self.seq, "step": self.step,
                "request": self.request, "kind": self.kind,
                "wall": self.wall, "attrs": dict(self.attrs)}


class FlightRecorder:
    """Bounded ring of ``FlightEvent``s plus the query/export surface.

    One recorder per engine (pass ``flight_recorder=`` to
    ``ServingEngine``; the engine's default is a DISABLED instance so
    the emit sites stay uniform at the one-bool-test cost).  Not
    thread-safe by design: the serving scheduler is single-threaded
    and every emit site runs on it.
    """

    def __init__(self, capacity: int = 8192, enabled: bool = True,
                 clock=None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._enabled = bool(enabled)
        self._clock = clock if clock is not None else time.perf_counter
        self._clock_explicit = clock is not None
        self._ring: deque = deque(maxlen=self.capacity)
        self._seq = 0
        self.dropped = 0

    def bind_clock(self, clock):
        """Adopt the owning engine's clock UNLESS this recorder was
        constructed with an explicit one — so event wall times and the
        engine's request arrival/finish times share one time base even
        for a user-constructed recorder (a replay/fake engine clock
        included), while a deliberately different recorder clock is
        respected."""
        if not self._clock_explicit:
            self._clock = clock

    # -- lifecycle --
    def enable(self):
        self._enabled = True

    def disable(self):
        """Freeze the recorder: ``emit`` becomes one attribute load +
        bool test (the same <2% decode-loop contract as a disabled
        MetricsRegistry)."""
        self._enabled = False

    @property
    def enabled(self) -> bool:
        return self._enabled

    # -- recording --
    def emit(self, kind: str, request: int, step: int, /, **attrs):
        # positional-only core so attrs may reuse the names (the fleet
        # monitor's "alert" events carry a kind= attr)
        if not self._enabled:
            return
        if kind not in EVENT_KINDS:
            raise ValueError(
                f"unknown flight-recorder event kind {kind!r} — known "
                f"kinds: {sorted(EVENT_KINDS)}")
        if len(self._ring) == self.capacity:
            self.dropped += 1          # deque drops the oldest on append
        self._ring.append(FlightEvent(
            self._seq, int(step), int(request), kind, self._clock(),
            attrs))
        self._seq += 1

    # -- queries --
    def events(self) -> List[FlightEvent]:
        return list(self._ring)

    def timeline(self, request_id: int) -> List[FlightEvent]:
        """This request's events, in emission order."""
        return [e for e in self._ring if e.request == request_id]

    def request_ids(self) -> List[int]:
        return sorted({e.request for e in self._ring
                       if e.request != ENGINE_EVENT})

    def explain(self, request_id: int) -> str:
        return explain_events(
            FlightRecord(self._ring, dropped=self.dropped,
                         capacity=self.capacity), request_id)

    # -- export --
    def export(self, path: str) -> dict:
        """Write the ring as JSON; ``dropped`` records how many events
        overflowed out of the ring, so a consumer can tell a complete
        record from a tail.  Returns the written header."""
        header = {"version": 1, "capacity": self.capacity,
                  "dropped": self.dropped, "n_events": len(self._ring)}
        with open(path, "w") as f:
            json.dump({**header,
                       "events": [e.as_dict() for e in self._ring]}, f)
        return header

    def chrome_events(self) -> list:
        """The ring as HostTracer-style event tuples ``(kind, t0, t1,
        tid, value, name)`` — instants on tid = request id (one
        Perfetto lane per request; engine-scoped events ride lane -1),
        attrs ``;k=v``-encoded into the name exactly like ``span()``
        does, so ``merge_chrome_traces(out, host=rec.chrome_events())``
        decodes them into Perfetto args.  Times convert from the
        recorder clock (seconds) to the tracer's ns."""
        out = []
        for e in self._ring:
            t = int(e.wall * 1e9)
            name = format_span_name(
                f"flightrec.{e.kind}", {"request": e.request,
                                        "step": e.step, **e.attrs})
            out.append((1, t, t, e.request, 0, name))
        return out

    def export_chrome_trace(self, out_path: str, host=None,
                            device_trace_dir: Optional[str] = None
                            ) -> dict:
        """One-call Perfetto export: the flight-recorder lanes plus
        optional host-tracer events (a list of event tuples) and the
        jax.profiler device dump, through ``merge_chrome_traces``."""
        from .spans import merge_chrome_traces
        events = self.chrome_events() + list(host or [])
        return merge_chrome_traces(out_path, host=events,
                                   device_trace_dir=device_trace_dir)


def events_from_record(record: dict) -> List[FlightEvent]:
    """The event list of an already-parsed export dict — the shared
    decoder behind ``load_flight_record`` and consumers that need the
    header too (the CLI reads ``dropped``) without parsing twice."""
    return [FlightEvent(e["seq"], e["step"], e["request"], e["kind"],
                        e["wall"], dict(e.get("attrs", {})))
            for e in record.get("events", [])]


class FlightRecord(list):
    """The loaded form of an export: a plain event list (full ``list``
    behavior, so every pre-existing consumer indexes/iterates it
    unchanged) that ALSO round-trips the export header — most
    importantly ``dropped``.  A stitched fleet story must know when a
    replica's ring overflowed: its missing early events are HOLES, not
    absence, and ``explain_events`` warns instead of narrating a
    partial lifecycle as if it were whole."""

    def __init__(self, events=(), *, dropped: int = 0,
                 capacity: Optional[int] = None, version: int = 1):
        super().__init__(events)
        self.dropped = int(dropped)
        self.capacity = capacity
        self.version = int(version)


def load_flight_record(path: str) -> FlightRecord:
    """Inverse of ``FlightRecorder.export``: the event list (attrs as
    plain dicts) in emission order, as a :class:`FlightRecord` carrying
    the header's ``dropped``/``capacity`` alongside."""
    with open(path) as f:
        record = json.load(f)
    return FlightRecord(
        events_from_record(record),
        dropped=int(record.get("dropped", 0)),
        capacity=record.get("capacity"),
        version=int(record.get("version", 1)))


def _plural(n: int, noun: str) -> str:
    return f"{n} {noun}{'' if n == 1 else 's'}"


def explain_events(events: List[FlightEvent], request_id: int) -> str:
    """Render one request's lifecycle as a human-readable sentence —
    "waited 3 steps behind req 7, preempted at step 12, resumed via 6
    host blocks, 9 spec positions rejected".  Works on any event list
    (a live recorder's ring or a loaded export), and uses OTHER
    requests' events too: "behind req 7" is derived from admissions
    that happened between this request's submit and its admit, so the
    recorder needs no extra queue bookkeeping.

    Returns a diagnostic string for unknown ids instead of raising —
    the CLI points this at arbitrary exports, and "not in this record
    (ring dropped N events)" is the honest answer there.  When the
    event list carries a ``dropped`` attribute (a loaded
    :class:`FlightRecord`, or the live recorder via ``explain()``),
    a non-zero drop count is surfaced in the rendering — an
    overflowed ring's story has holes and must say so."""
    dropped = int(getattr(events, "dropped", 0) or 0)
    tl = [e for e in events if e.request == request_id]
    if not tl:
        note = (f"; the ring dropped "
                f"{_plural(dropped, 'oldest event')}" if dropped else "")
        return (f"request {request_id}: no events in this record "
                f"(wrong id, or the ring dropped them)" + note)
    by_kind: Dict[str, List[FlightEvent]] = {}
    for e in tl:
        by_kind.setdefault(e.kind, []).append(e)
    parts: List[str] = []

    sub = by_kind.get("submit", [None])[0]
    admits = by_kind.get("admit", [])
    if sub is not None:
        bits = [f"submitted at step {sub.step}"]
        for k in ("seq_len", "max_new", "priority"):
            if k in sub.attrs:
                bits.append(f"{k}={sub.attrs[k]}")
        parts.append(bits[0] + " (" + ", ".join(bits[1:]) + ")"
                     if len(bits) > 1 else bits[0])
    for rt in by_kind.get("route", []):
        clause = f"routed to engine {rt.attrs.get('engine', '?')}"
        details = []
        aff = int(rt.attrs.get("affinity", 0))
        if aff:
            details.append(f"prefix affinity {aff} tokens")
        if rt.attrs.get("adapter_hit"):
            details.append("adapter resident")
        if "policy" in rt.attrs:
            details.append(f"policy {rt.attrs['policy']}")
        if "reason" in rt.attrs and not details:
            details.append(f"by {rt.attrs['reason']}")
        if details:
            clause += " (" + ", ".join(details) + ")"
        parts.append(clause)
    if admits:
        adm = admits[0]
        clause = f"admitted at step {adm.step} into slot " \
                 f"{adm.attrs.get('slot', '?')}"
        # multi-tenant LoRA serving: which adapter the request decodes
        # through and how far behind its fair share the tenant was at
        # the admission decision (a deterministic token count)
        if "adapter" in adm.attrs:
            clause += f" with adapter {adm.attrs['adapter']}"
        if "tenant" in adm.attrs:
            clause += (f" (tenant {adm.attrs['tenant']}, fair-share "
                       f"deficit {adm.attrs.get('deficit', 0)})")
        if sub is not None:
            waited = adm.step - sub.step
            ahead = sorted({
                e.request for e in events
                if e.kind == "admit" and e.request != request_id
                and (sub.seq < e.seq < adm.seq)})
            # waited == 1 means "admitted at the first step after
            # submission" — only a longer wait (or a queue-jump) is
            # worth a clause
            if waited > 1 or ahead:
                clause = (f"waited {_plural(waited, 'step')}"
                          + (f" behind req "
                             f"{', '.join(str(r) for r in ahead)}"
                             if ahead else "")
                          + f", {clause}")
        parts.append(clause)
    for h in by_kind.get("prefix_hit", []):
        parts.append(
            f"prefix hit ({h.attrs.get('tier', '?')}): "
            f"{_plural(int(h.attrs.get('blocks', 0)), 'cached block')}"
            f" / {h.attrs.get('tokens', 0)} tokens mapped at step "
            f"{h.step}")
    n_chunks = len(by_kind.get("prefill_chunk", []))
    if n_chunks:
        parts.append(f"prefilled in {_plural(n_chunks, 'chunk')}")
    for p in by_kind.get("preempt", []):
        parts.append(
            f"preempted at step {p.step} "
            f"({_plural(int(p.attrs.get('blocks', 0)), 'block')} to "
            f"host, reason={p.attrs.get('reason', '?')})")
    for s in by_kind.get("swap_in", []):
        if s.attrs.get("reason") == "preempt":
            parts.append(
                f"resumed at step {s.step} via "
                f"{_plural(int(s.attrs.get('blocks', 0)), 'host block')}")
        else:
            parts.append(
                f"promoted {_plural(int(s.attrs.get('blocks', 0)), 'host block')} "
                f"at step {s.step} (cache hit)")
    # failover lifecycle (router health model): replica failure, then
    # the recovery path — exact-bytes migration or deterministic
    # recompute/requeue — or the terminal budget exhaustion
    for f in by_kind.get("fail", []):
        if f.attrs.get("terminal"):
            nr = int(f.attrs.get("retries", 0))
            parts.append(
                f"failed terminally at step {f.step} (retry budget "
                f"exhausted after {nr} "
                f"{'retry' if nr == 1 else 'retries'})")
        else:
            parts.append(
                f"replica e{f.attrs.get('engine', '?')} failed under "
                f"{f.attrs.get('fault', '?')} at step {f.step}")
    for mg in by_kind.get("migrate", []):
        parts.append(
            f"failed over to engine {mg.attrs.get('engine', '?')} "
            f"(migrated "
            f"{_plural(int(mg.attrs.get('blocks', 0)), 'block')} "
            f"at exact bytes)")
    for ho in by_kind.get("handoff", []):
        src = ho.attrs.get("src")
        if src is not None:
            # the router's vantage: it knows both endpoints
            parts.append(
                f"prefilled on engine {src}, handed off "
                f"{_plural(int(ho.attrs.get('blocks', 0)), 'block')} "
                f"to engine {ho.attrs.get('engine', '?')} at "
                f"chunk-final")
        else:
            # a single engine's vantage: it only knows it let go
            parts.append(
                f"handed off "
                f"{_plural(int(ho.attrs.get('blocks', 0)), 'block')} "
                f"at chunk-final for decode elsewhere")
    for rt in by_kind.get("retry", []):
        how = ("recomputed from prompt"
               if rt.attrs.get("path") == "recompute"
               else "re-queued")
        parts.append(
            f"failed over to engine {rt.attrs.get('engine', '?')} "
            f"({how}, attempt {rt.attrs.get('attempt', '?')})")
    verifies = by_kind.get("spec_verify", [])
    if verifies:
        rejected = sum(int(v.attrs.get("rejected", 0)) for v in verifies)
        accepted = sum(int(v.attrs.get("accepted", 0)) for v in verifies)
        parts.append(
            f"{_plural(accepted, 'spec position')} accepted / "
            f"{rejected} rejected over "
            f"{_plural(len(verifies), 'verify forward')}")
    blocks_ev = by_kind.get("decode_block", [])
    if blocks_ev:
        clause = f"rode {_plural(len(blocks_ev), 'decode block')}"
        # harvest lag (dispatch-ahead engines): events are stamped
        # with the DISPATCH step; ``lag`` says how many steps later
        # the outputs were forced to host — a deterministic step
        # delta, never wall time
        lags = [int(e.attrs.get("lag", 0)) for e in blocks_ev]
        n_lag = sum(1 for v in lags if v)
        if n_lag:
            # "lag <= K": a depth-S pipeline harvests each dispatch up
            # to S steps after it was enqueued; max(lags) is the
            # deepest deferral this request actually saw
            clause += (f" ({n_lag} harvested dispatch-ahead, lag <= "
                       f"{_plural(max(lags), 'step')})")
        parts.append(clause)
    for kind, verb in (("finish", "finished"), ("timeout", "timed out"),
                       ("shed", "shed"), ("cancel", "cancelled")):
        for e in by_kind.get(kind, []):
            extra = ""
            if kind == "finish" and "tokens" in e.attrs:
                extra = f" after {_plural(int(e.attrs['tokens']), 'token')}"
            if kind == "cancel" and "phase" in e.attrs:
                extra = f" from phase {e.attrs['phase']}"
            flag = int(e.attrs.get("lag", 0))
            if kind == "finish" and flag:
                # the finish-bitmap poll (dispatch-ahead depth >= 2):
                # the device flipped the row's finish bit inside the
                # dispatch of step N; the host observed it at the
                # deferred harvest, ``lag`` steps later
                parts.append(
                    f"finished on device at step {e.step}, host "
                    f"observed at step {e.step + flag}{extra}")
            else:
                parts.append(f"{verb} at step {e.step}{extra}")
    text = f"request {request_id}: " + "; ".join(parts)
    if dropped:
        text += (f" [ring dropped {_plural(dropped, 'oldest event')} — "
                 f"the early story may have holes]")
    return text
