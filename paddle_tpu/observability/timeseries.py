"""Step-indexed time-series over the metrics registry.

``MetricsRegistry.snapshot()`` answers "where are the counters NOW";
``diff_snapshots`` answers "what moved between two moments".  Neither
answers the operational question a fleet dashboard asks — "what did
queue depth / token throughput / TPOT look like over the last K
steps" — without the caller keeping its own snapshot history.
``TimeSeriesRecorder`` is that history, kept deliberately in the
repo's deterministic idiom:

- **step-indexed, not wall-indexed** — samples are keyed on the
  engine/router scheduler step the caller passes to ``sample(step)``,
  never on the clock.  Two replays of one trace sample at identical
  steps and produce byte-identical series; the per-sample ``wall``
  field is report-only (the ONE field excluded from determinism
  comparisons, exactly like ``FlightEvent.wall``).
- **bounded** — the ring is a ``deque(maxlen=capacity)``: overflow
  drops the OLDEST samples and ``dropped`` counts the loss, so an
  export is never silently partial (the ``FlightRecorder`` contract).
- **selected instruments** — the recorder samples a caller-chosen
  instrument subset (default: everything registered at first sample),
  each sample storing the instrument's cumulative values per label
  cell.  Cumulative, not deltas: a window aggregate between ANY two
  ring positions is then a subtraction, and a dropped sample loses
  resolution, not mass.
- **window aggregates** — ``aggregates()`` reduces the ring to
  counter deltas + per-step rates, gauge last/min/max (max is the
  honest PER-WINDOW high-water mark ``diff_snapshots`` cannot give —
  its ``hwm`` is process-lifetime), and histogram-delta quantiles via
  the same bucket interpolation the registry exports.

``sample()`` on a disabled recorder is one attribute load + bool test
(the metrics/flightrec disabled contract); the enabled path costs one
``_snap()`` per selected instrument, so keep the selection tight when
sampling every scheduler step.
"""

from __future__ import annotations

import json
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,
                      _quantile_from_buckets, get_registry)


class TimeSeriesRecorder:
    """Bounded ring of step-indexed instrument samples.

    One recorder per registry view (pass ``timeseries=`` to ``Router``
    to have it sampled once per router step).  Not thread-safe by
    design: the serving scheduler is single-threaded and the sample
    site runs on it.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None, *,
                 capacity: int = 512,
                 instruments: Optional[Sequence[str]] = None,
                 enabled: bool = True, clock=None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self._registry = (registry if registry is not None
                          else get_registry())
        self.capacity = int(capacity)
        self._enabled = bool(enabled)
        self._clock = clock if clock is not None else time.perf_counter
        # None = "everything registered at first sample" (resolved
        # lazily so construction order vs. instrument registration
        # does not matter); an explicit selection stays fixed
        self._names: Optional[List[str]] = (
            None if instruments is None else sorted(instruments))
        self._ring: deque = deque(maxlen=self.capacity)
        self.dropped = 0
        # histogram bucket bounds per name, captured at first sight so
        # aggregates can interpolate quantiles from stored buckets
        self._bounds: Dict[str, tuple] = {}

    # -- lifecycle --
    def enable(self):
        self._enabled = True

    def disable(self):
        """Freeze the recorder: ``sample`` becomes one attribute load
        + bool test (the <2% decode-loop contract)."""
        self._enabled = False

    @property
    def enabled(self) -> bool:
        return self._enabled

    @property
    def instruments(self) -> List[str]:
        """The sampled instrument names (resolved selection)."""
        return list(self._resolve_names())

    def __len__(self) -> int:
        return len(self._ring)

    def _resolve_names(self) -> List[str]:
        if self._names is None:
            self._names = self._registry.names()
        return self._names

    # -- recording --
    def sample(self, step: int):
        """Record one sample keyed on the caller's scheduler ``step``.
        Every stored field except ``wall`` derives from instrument
        state — replaying a trace reproduces the series byte for
        byte."""
        if not self._enabled:
            return
        data: Dict[str, dict] = {}
        for name in self._resolve_names():
            inst = self._registry.get(name)
            if inst is None:
                continue
            snap = inst._snap()
            if isinstance(inst, Counter):
                data[name] = {"values": dict(snap["values"])}
            elif isinstance(inst, Gauge):
                data[name] = {"values": dict(snap["values"]),
                              "hwm": dict(snap["hwm"])}
            elif isinstance(inst, Histogram):
                if name not in self._bounds:
                    self._bounds[name] = tuple(snap["le"])
                data[name] = {"values": {
                    lk: {"count": c["count"], "sum": c["sum"],
                         "buckets": list(c["buckets"])}
                    for lk, c in snap["values"].items()}}
        if len(self._ring) == self.capacity:
            self.dropped += 1        # deque drops the oldest on append
        self._ring.append({"step": int(step), "wall": self._clock(),
                           "data": data})

    # -- queries --
    def samples(self) -> List[dict]:
        return list(self._ring)

    def steps(self) -> List[int]:
        return [s["step"] for s in self._ring]

    def series(self, name: str, label: str = "") -> List[tuple]:
        """One instrument cell as ``[(step, value), ...]`` over the
        ring — cumulative totals for counters, levels for gauges.
        ``label`` is the snapshot label key (``"tenant=a"``; empty for
        unlabeled instruments); steps where the cell did not exist
        yet are skipped."""
        out = []
        for s in self._ring:
            cell = s["data"].get(name, {}).get("values", {})
            if label in cell:
                v = cell[label]
                out.append((s["step"],
                            v if not isinstance(v, dict)
                            else v["count"]))
        return out

    def rates(self, name: str, label: str = "") -> List[tuple]:
        """Per-step rate between consecutive samples of a counter
        cell: ``[(step, delta / steps_elapsed), ...]``."""
        pts = self.series(name, label)
        out = []
        for (s0, v0), (s1, v1) in zip(pts, pts[1:]):
            dt = max(1, s1 - s0)
            out.append((s1, (v1 - v0) / dt))
        return out

    def aggregates(self) -> dict:
        """Whole-window reduction (oldest surviving sample -> newest):
        counters -> ``delta`` + ``rate_per_step``; gauges -> ``last``
        / ``min`` / ``max`` of the SAMPLED values (``max`` is the
        per-window high-water mark); histograms -> delta
        count/sum/p50/p95/p99 interpolated from the bucket deltas
        (cells whose window delta is empty drop, mirroring
        ``diff_snapshots``)."""
        if not self._ring:
            return {"steps": 0, "instruments": {}}
        first, last = self._ring[0], self._ring[-1]
        steps = max(1, last["step"] - first["step"])
        insts: Dict[str, dict] = {}
        for name in sorted(last["data"]):
            cur = last["data"][name]["values"]
            base = first["data"].get(name, {}).get("values", {})
            if name in self._bounds:                 # histogram
                bounds = self._bounds[name]
                cells = {}
                for lk, c in cur.items():
                    p = base.get(lk)
                    counts = list(c["buckets"])
                    count, total = c["count"], c["sum"]
                    if p is not None:
                        counts = [a - b for a, b in
                                  zip(counts, p["buckets"])]
                        count -= p["count"]
                        total -= p["sum"]
                    if count <= 0:
                        continue
                    cells[lk] = {
                        "count": count, "sum": total,
                        "p50": _quantile_from_buckets(
                            0.50, bounds, counts),
                        "p95": _quantile_from_buckets(
                            0.95, bounds, counts),
                        "p99": _quantile_from_buckets(
                            0.99, bounds, counts)}
                if cells:
                    insts[name] = {"type": "histogram", "values": cells}
            elif "hwm" in last["data"][name]:        # gauge
                mins: Dict[str, float] = {}
                maxs: Dict[str, float] = {}
                for s in self._ring:
                    for lk, v in s["data"].get(name, {}) \
                            .get("values", {}).items():
                        if lk not in mins or v < mins[lk]:
                            mins[lk] = v
                        if lk not in maxs or v > maxs[lk]:
                            maxs[lk] = v
                insts[name] = {"type": "gauge", "last": dict(cur),
                               "min": mins, "max": maxs}
            else:                                    # counter
                delta = {lk: v - base.get(lk, 0)
                         for lk, v in cur.items()
                         if v - base.get(lk, 0)}
                if delta:
                    insts[name] = {
                        "type": "counter", "delta": delta,
                        "rate_per_step": {lk: d / steps
                                          for lk, d in delta.items()}}
        return {"steps": steps,
                "first_step": first["step"], "last_step": last["step"],
                "samples": len(self._ring), "dropped": self.dropped,
                "instruments": insts}

    # -- export --
    def to_dict(self, *, drop_wall: bool = False) -> dict:
        """The full ring as a JSON-ready dict.  ``drop_wall=True``
        zeroes the report-only wall stamps — the canonical form two
        replays of one trace must agree on byte for byte."""
        samples = []
        for s in self._ring:
            samples.append({"step": s["step"],
                            "wall": 0.0 if drop_wall else s["wall"],
                            "data": s["data"]})
        return {"version": 1, "capacity": self.capacity,
                "dropped": self.dropped,
                "instruments": list(self._resolve_names()),
                "bounds": {k: list(v)
                           for k, v in sorted(self._bounds.items())},
                "samples": samples}

    def export(self, path: str) -> dict:
        """Write the ring as JSON (sorted keys, so the file itself is
        deterministic modulo wall); returns the header fields."""
        d = self.to_dict()
        with open(path, "w") as f:
            json.dump(d, f, sort_keys=True)
        return {"version": d["version"], "capacity": d["capacity"],
                "dropped": d["dropped"], "n_samples": len(d["samples"])}
