"""Samplers (analogue of python/paddle/io/dataloader/batch_sampler.py etc.)."""

from __future__ import annotations

import math

import numpy as np

__all__ = ["Sampler", "SequenceSampler", "RandomSampler", "BatchSampler",
           "DistributedBatchSampler", "WeightedRandomSampler",
           "SubsetRandomSampler"]


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        rng = np.random.default_rng()
        if self.replacement:
            return iter(rng.integers(0, n, self.num_samples).tolist())
        return iter(rng.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices, generator=None):
        super().__init__(indices)
        self.indices = list(indices)

    def __iter__(self):
        rng = np.random.default_rng()
        return iter(rng.permutation(len(self.indices)).tolist())

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        rng = np.random.default_rng()
        return iter(rng.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p).tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        if sampler is None:
            sampler = RandomSampler(dataset) if shuffle \
                else SequenceSampler(dataset)
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Rank-sharded batch sampler (reference:
    python/paddle/io/dataloader/dataloader_iter.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import get_rank, get_world_size
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None \
            else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.epoch)
            indices = rng.permutation(n)
        indices = np.concatenate(
            [indices, indices[:self.total_size - n]])  # pad to even
        indices = indices[self.local_rank::self.nranks].tolist()
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch
