"""DataLoader (analogue of python/paddle/io/dataloader/dataloader_iter.py).

Host pipeline, two worker modes mirroring the reference's
``_DataLoaderIterSingleProcess`` / ``_DataLoaderIterMultiProcess``
(``dataloader_iter.py:358``):

- process mode: forked WORKER PROCESSES with per-worker index queues and
  a shared result queue — decode-heavy, GIL-bound ``__getitem__``
  pipelines scale across cores.  Order is restored with a reorder buffer;
  worker crashes are detected by exit-code polling instead of hanging.
  Workers are forked (like the reference/torch on POSIX) so datasets need
  no pickling; children must not touch jax/device state — fetch+collate
  stay numpy-only.  Because forking after the TPU runtime is live is
  unsafe, this mode auto-enables only while no non-CPU JAX backend has
  been initialized (``use_process_workers=None`` default); pass ``True``
  to request it explicitly (falls back to threads with a warning when
  unsafe) or ``False`` to force threads.
- thread mode: worker threads running the fetch through the native C++
  WorkQueue/BlockingQueue pair — right when the transform is numpy-bound
  (GIL released) and fork cost matters, and always safe.

The iterator converts numpy batches to device Tensors on the consumer
side in both modes.
"""

from __future__ import annotations

import multiprocessing as mp
import queue
import threading
import warnings
from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .dataset import IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "default_collate_fn", "get_worker_info"]

_worker_info = threading.local()


def _fork_is_safe():
    """True while every JAX backend initialized in this process is the CPU
    one — forking with libtpu/grpc threads live can deadlock the child."""
    try:
        from jax._src import xla_bridge
        backends = getattr(xla_bridge, "_backends", None)
        if backends is None:  # private API moved: assume unsafe
            return False
        return all(name == "cpu" for name in backends)
    except Exception:
        return False


def get_worker_info():
    return getattr(_worker_info, "info", None)


class WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


class _WorkerError:
    """Picklable error marker crossing the process boundary."""

    def __init__(self, msg):
        self.msg = msg


class _WorkerDone:
    def __init__(self, wid):
        self.wid = wid


def default_collate_fn(batch):
    """Stack samples into batched numpy arrays (mirrors the reference's
    default_collate_fn field-wise recursion)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s._value) for s in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        transposed = list(zip(*batch))
        return type(sample)(default_collate_fn(list(f)) for f in transposed)
    return np.asarray(batch)


def _to_tensor(value):
    if isinstance(value, np.ndarray):
        return Tensor(jnp.asarray(value))
    if isinstance(value, dict):
        return {k: _to_tensor(v) for k, v in value.items()}
    if isinstance(value, (tuple, list)):
        return type(value)(_to_tensor(v) for v in value)
    return value


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, use_process_workers=None):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 2)
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout or None
        self.use_process_workers = use_process_workers
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    def _fetch(self, indices):
        batch = [self.dataset[i] for i in indices]
        return self.collate_fn(batch)

    def _iter_iterable(self):
        _worker_info.info = WorkerInfo(0, max(self.num_workers, 1), self.dataset)
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield _to_tensor(self.collate_fn(batch))
                batch = []
        if batch and not self.drop_last:
            yield _to_tensor(self.collate_fn(batch))

    def _iter_sync(self):
        for indices in self.batch_sampler:
            yield _to_tensor(self._fetch(indices))

    def _iter_workers(self):
        # Native prefetch pipeline: C++ BlockingQueue bounds the in-flight
        # batches (≙ LoDTensorBlockingQueue feeding the buffered reader) and
        # a C++ WorkQueue thread pool runs the fetch+collate tasks
        # (≙ new_executor workqueue). Waits happen in native code with the
        # GIL released; numpy collation overlaps across workers.
        from .. import runtime as rt

        out_q = rt.BlockingQueue(self.prefetch_factor * self.num_workers)
        idx_q: "queue.Queue" = queue.Queue()
        batches = list(self.batch_sampler)
        for i, b in enumerate(batches):
            idx_q.put((i, b))
        n_batches = len(batches)
        pool = rt.WorkQueue(self.num_workers)

        def worker(wid):
            # every failure mode (init fn, fetch, collate) is surfaced to the
            # consumer through the queue so the iterator never hangs silently
            try:
                _worker_info.info = WorkerInfo(wid, self.num_workers,
                                               self.dataset)
                if self.worker_init_fn is not None:
                    self.worker_init_fn(wid)
            except Exception as e:
                try:
                    i, _ = idx_q.get_nowait()
                except queue.Empty:
                    return
                try:
                    out_q.push((i, e))
                except rt.QueueClosed:
                    pass
                return
            while not out_q.closed:
                try:
                    i, indices = idx_q.get_nowait()
                except queue.Empty:
                    return
                try:
                    item = (i, self._fetch(indices))
                except Exception as e:  # surface worker errors to the consumer
                    item = (i, e)
                try:
                    out_q.push(item)
                except rt.QueueClosed:
                    return

        for w in range(self.num_workers):
            pool.submit(lambda w=w: worker(w))
        try:
            # reorder to preserve batch order
            pending = {}
            next_idx = 0
            received = 0
            while received < n_batches:
                i, data = out_q.pop(timeout=self.timeout)
                if rt.HostTracer.is_enabled():
                    rt.HostTracer.counter("dataloader_queue_depth", out_q.size())
                received += 1
                pending[i] = data
                while next_idx in pending:
                    item = pending.pop(next_idx)
                    next_idx += 1
                    if isinstance(item, Exception):
                        raise item
                    yield _to_tensor(item)
        finally:
            out_q.close()
            pool.shutdown()

    def _iter_multiprocess(self):
        """Forked worker processes (reference _DataLoaderIterMultiProcess,
        dataloader_iter.py:358): per-worker index queues assigned
        round-robin (deterministic), one shared result queue, a reorder
        buffer on the consumer, and liveness polling so a dead worker
        raises instead of hanging the iterator."""
        ctx = mp.get_context("fork")
        batches = list(self.batch_sampler)
        n_batches = len(batches)
        nw = self.num_workers
        index_queues = [ctx.Queue() for _ in range(nw)]
        result_q = ctx.Queue(maxsize=self.prefetch_factor * nw)
        for i, b in enumerate(batches):
            index_queues[i % nw].put((i, list(b)))
        for q in index_queues:
            q.put(None)  # sentinel: no more work

        dataset = self.dataset
        collate = self.collate_fn
        init_fn = self.worker_init_fn

        def worker_main(wid, idx_q, out_q):
            try:
                _worker_info.info = WorkerInfo(wid, nw, dataset)
                if init_fn is not None:
                    init_fn(wid)
                while True:
                    task = idx_q.get()
                    if task is None:
                        break
                    i, indices = task
                    try:
                        data = collate([dataset[j] for j in indices])
                    except Exception as e:  # surface to the consumer
                        data = _WorkerError(repr(e))
                    out_q.put((i, data))
            except KeyboardInterrupt:
                # dying mid-write: don't block process exit on the feeder
                out_q.cancel_join_thread()

        procs = []
        for w in range(nw):
            p = ctx.Process(target=worker_main,
                            args=(w, index_queues[w], result_q),
                            daemon=True)
            p.start()
            procs.append(p)

        try:
            pending = {}
            next_idx = 0
            received = 0
            while received < n_batches:
                try:
                    i, data = result_q.get(timeout=self.timeout or 5.0)
                except queue.Empty:
                    # normal exit (exitcode 0) is not death: a finished
                    # worker may coexist with a slow one mid-epoch
                    crashed = [p.pid for p in procs
                               if p.exitcode not in (None, 0)]
                    if crashed:
                        raise RuntimeError(
                            f"DataLoader worker(s) {crashed} exited "
                            "unexpectedly") from None
                    if all(p.exitcode == 0 for p in procs):
                        raise RuntimeError(
                            "DataLoader workers all finished but "
                            f"{n_batches - received} batch(es) were never "
                            "received") from None
                    if self.timeout:
                        raise RuntimeError(
                            f"DataLoader timed out after {self.timeout}s "
                            "waiting for a batch") from None
                    continue
                received += 1
                pending[i] = data
                while next_idx in pending:
                    item = pending.pop(next_idx)
                    next_idx += 1
                    if isinstance(item, _WorkerError):
                        raise RuntimeError(
                            f"DataLoader worker raised: {item.msg}")
                    yield _to_tensor(item)
        finally:
            for p in procs:
                if p.is_alive():
                    p.terminate()
            for p in procs:
                p.join(timeout=1.0)
            for q in index_queues:
                q.cancel_join_thread()
                q.close()
            result_q.cancel_join_thread()
            result_q.close()

    def _resolve_process_workers(self):
        """Forking a process whose TPU runtime (libtpu/grpc threads) is live
        can deadlock or crash the child, so process workers are only used
        when every initialized JAX backend is the CPU one. use_process_workers
        None=auto, True=requested (falls back with a warning when unsafe),
        False=threads."""
        if self.use_process_workers is False:
            return False
        safe = _fork_is_safe()
        if self.use_process_workers and not safe:
            fallback = ("sequential in-process iteration" if self._iterable
                        else "native thread workers")
            warnings.warn(
                "DataLoader(use_process_workers=True) but a non-CPU JAX "
                "backend is already initialized in this process; forking now "
                f"is unsafe — falling back to {fallback}.",
                RuntimeWarning)
        return safe

    def __iter__(self):
        use_proc = self.num_workers > 0 and self._resolve_process_workers()
        if self._iterable:
            if use_proc:
                return self._iter_iterable_multiprocess()
            return self._iter_iterable()
        if self.num_workers > 0:
            if use_proc:
                return self._iter_multiprocess()
            return self._iter_workers()
        return self._iter_sync()

    def _iter_iterable_multiprocess(self):
        """IterableDataset over forked workers: each worker iterates its
        shard (WorkerInfo tells it which), builds whole batches, and the
        consumer yields them in arrival order (the reference likewise
        leaves cross-worker order undefined for iterable datasets)."""
        ctx = mp.get_context("fork")
        nw = self.num_workers
        result_q = ctx.Queue(maxsize=self.prefetch_factor * nw)
        dataset = self.dataset
        collate = self.collate_fn
        init_fn = self.worker_init_fn
        batch_size = self.batch_size
        drop_last = self.drop_last

        def worker_main(wid, out_q):
            try:
                _worker_info.info = WorkerInfo(wid, nw, dataset)
                if init_fn is not None:
                    init_fn(wid)
                batch = []
                try:
                    for sample in dataset:
                        batch.append(sample)
                        if len(batch) == batch_size:
                            out_q.put(collate(batch))
                            batch = []
                    if batch and not drop_last:
                        out_q.put(collate(batch))
                except Exception as e:
                    out_q.put(_WorkerError(repr(e)))
                out_q.put(_WorkerDone(wid))
            except KeyboardInterrupt:
                # dying mid-write: don't block process exit on the feeder
                out_q.cancel_join_thread()

        procs = []
        for w in range(nw):
            p = ctx.Process(target=worker_main, args=(w, result_q),
                            daemon=True)
            p.start()
            procs.append(p)

        try:
            done = 0
            while done < nw:
                try:
                    item = result_q.get(timeout=self.timeout or 5.0)
                except queue.Empty:
                    crashed = [p.pid for p in procs
                               if p.exitcode not in (None, 0)]
                    if crashed:
                        raise RuntimeError(
                            f"DataLoader worker(s) {crashed} exited "
                            "unexpectedly") from None
                    continue
                if isinstance(item, _WorkerDone):
                    done += 1
                    continue
                if isinstance(item, _WorkerError):
                    raise RuntimeError(
                        f"DataLoader worker raised: {item.msg}")
                yield _to_tensor(item)
        finally:
            for p in procs:
                if p.is_alive():
                    p.terminate()
            for p in procs:
                p.join(timeout=1.0)
            result_q.cancel_join_thread()
            result_q.close()
