"""DataLoader (analogue of python/paddle/io/dataloader/dataloader_iter.py).

Host pipeline: worker threads fetch+collate numpy batches into a bounded
queue; the iterator converts to device Tensors.  Threads (not processes) are
the right default on TPU VMs — input work is numpy-bound and the GIL is
released inside numpy, while device transfers overlap via the queue
(reference equivalent: LoDTensorBlockingQueue + multiprocess workers).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .dataset import IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "default_collate_fn", "get_worker_info"]

_worker_info = threading.local()


def get_worker_info():
    return getattr(_worker_info, "info", None)


class WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


def default_collate_fn(batch):
    """Stack samples into batched numpy arrays (mirrors the reference's
    default_collate_fn field-wise recursion)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s._value) for s in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        transposed = list(zip(*batch))
        return type(sample)(default_collate_fn(list(f)) for f in transposed)
    return np.asarray(batch)


def _to_tensor(value):
    if isinstance(value, np.ndarray):
        return Tensor(jnp.asarray(value))
    if isinstance(value, dict):
        return {k: _to_tensor(v) for k, v in value.items()}
    if isinstance(value, (tuple, list)):
        return type(value)(_to_tensor(v) for v in value)
    return value


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 2)
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout or None
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    def _fetch(self, indices):
        batch = [self.dataset[i] for i in indices]
        return self.collate_fn(batch)

    def _iter_iterable(self):
        _worker_info.info = WorkerInfo(0, max(self.num_workers, 1), self.dataset)
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield _to_tensor(self.collate_fn(batch))
                batch = []
        if batch and not self.drop_last:
            yield _to_tensor(self.collate_fn(batch))

    def _iter_sync(self):
        for indices in self.batch_sampler:
            yield _to_tensor(self._fetch(indices))

    def _iter_workers(self):
        # Native prefetch pipeline: C++ BlockingQueue bounds the in-flight
        # batches (≙ LoDTensorBlockingQueue feeding the buffered reader) and
        # a C++ WorkQueue thread pool runs the fetch+collate tasks
        # (≙ new_executor workqueue). Waits happen in native code with the
        # GIL released; numpy collation overlaps across workers.
        from .. import runtime as rt

        out_q = rt.BlockingQueue(self.prefetch_factor * self.num_workers)
        idx_q: "queue.Queue" = queue.Queue()
        batches = list(self.batch_sampler)
        for i, b in enumerate(batches):
            idx_q.put((i, b))
        n_batches = len(batches)
        pool = rt.WorkQueue(self.num_workers)

        def worker(wid):
            # every failure mode (init fn, fetch, collate) is surfaced to the
            # consumer through the queue so the iterator never hangs silently
            try:
                _worker_info.info = WorkerInfo(wid, self.num_workers,
                                               self.dataset)
                if self.worker_init_fn is not None:
                    self.worker_init_fn(wid)
            except Exception as e:
                try:
                    i, _ = idx_q.get_nowait()
                except queue.Empty:
                    return
                try:
                    out_q.push((i, e))
                except rt.QueueClosed:
                    pass
                return
            while not out_q.closed:
                try:
                    i, indices = idx_q.get_nowait()
                except queue.Empty:
                    return
                try:
                    item = (i, self._fetch(indices))
                except Exception as e:  # surface worker errors to the consumer
                    item = (i, e)
                try:
                    out_q.push(item)
                except rt.QueueClosed:
                    return

        for w in range(self.num_workers):
            pool.submit(lambda w=w: worker(w))
        try:
            # reorder to preserve batch order
            pending = {}
            next_idx = 0
            received = 0
            while received < n_batches:
                i, data = out_q.pop(timeout=self.timeout)
                if rt.HostTracer.is_enabled():
                    rt.HostTracer.counter("dataloader_queue_depth", out_q.size())
                received += 1
                pending[i] = data
                while next_idx in pending:
                    item = pending.pop(next_idx)
                    next_idx += 1
                    if isinstance(item, Exception):
                        raise item
                    yield _to_tensor(item)
        finally:
            out_q.close()
            pool.shutdown()

    def __iter__(self):
        if self._iterable:
            return self._iter_iterable()
        if self.num_workers > 0:
            return self._iter_workers()
        return self._iter_sync()
