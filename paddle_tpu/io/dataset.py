"""Datasets (analogue of python/paddle/io/dataloader/dataset.py)."""

from __future__ import annotations

import bisect

import numpy as np

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
           "ChainDataset", "Subset", "ConcatDataset", "random_split"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset is not indexable")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        lengths = {t.shape[0] for t in tensors}
        assert len(lengths) == 1, "tensors must share dim-0 length"
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for ds in self.datasets:
            item = ds[idx]
            out.extend(item if isinstance(item, tuple) else (item,))
        return tuple(out)

    def __len__(self):
        return min(len(ds) for ds in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for ds in self.datasets:
            yield from ds


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = 0 if ds_idx == 0 else self.cumulative_sizes[ds_idx - 1]
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    from ..core.generator import default_generator
    total = len(dataset)
    if all(isinstance(l, float) for l in lengths):
        lengths = [int(round(l * total)) for l in lengths]
        lengths[-1] = total - sum(lengths[:-1])
    assert sum(lengths) == total
    import jax.random as jrandom
    key = (generator or default_generator()).next_key()
    perm = np.asarray(jrandom.permutation(key, total))
    out, offset = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[offset:offset + l].tolist()))
        offset += l
    return out
