"""paddle_tpu.io — datasets and DataLoader (analogue of paddle.io).

The loader is a host-side pipeline: worker threads batch numpy data and a
prefetch queue overlaps host batching with device compute (the analogue of
the reference's LoDTensorBlockingQueue double-buffering,
``paddle/fluid/operators/reader/lod_tensor_blocking_queue.h:30``).
"""

from .dataset import (Dataset, IterableDataset, TensorDataset, ComposeDataset,
                      ChainDataset, Subset, ConcatDataset, random_split)
from .sampler import (Sampler, SequenceSampler, RandomSampler, BatchSampler,
                      DistributedBatchSampler, WeightedRandomSampler,
                      SubsetRandomSampler)
from .dataloader import DataLoader, default_collate_fn, get_worker_info

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "Subset", "ConcatDataset", "random_split", "Sampler",
    "SequenceSampler", "RandomSampler", "BatchSampler",
    "DistributedBatchSampler", "WeightedRandomSampler", "SubsetRandomSampler",
    "DataLoader", "default_collate_fn", "get_worker_info",
]
