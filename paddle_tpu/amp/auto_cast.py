"""auto_cast (analogue of python/paddle/amp/auto_cast.py:687)."""

from __future__ import annotations

import threading
from contextlib import contextmanager

import jax.numpy as jnp

from ..core import dispatch as _dispatch
from ..core.dtypes import convert_dtype

# op categories (mirroring python/paddle/amp/amp_lists.py)
WHITE_LIST = {
    "matmul", "linear", "conv1d", "conv2d", "conv3d", "bmm", "mv", "einsum",
    "addmm", "flash_attention", "sdpa", "conv1d_transpose", "conv2d_transpose",
    "conv3d_transpose", "lstm", "gru", "rnn_tanh",
}
BLACK_LIST = {
    "exp", "log", "log2", "log10", "log1p", "mean", "sum", "softmax",
    "log_softmax", "cross_entropy", "nll_loss", "layer_norm", "rms_norm",
    "batch_norm", "group_norm", "instance_norm", "norm", "cumsum", "logsumexp",
    "sigmoid_focal_loss", "bce_with_logits", "binary_cross_entropy", "pow",
    "mse_loss", "l1_loss", "kl_div", "softmax_with_cross_entropy", "erfinv",
    "acos", "asin", "cosh", "sinh", "tan", "atanh", "acosh", "asinh",
    "reciprocal", "rsqrt",
}


def white_list():
    return {"float16": {"O1": sorted(WHITE_LIST), "O2": sorted(WHITE_LIST)}}


def black_list():
    return {"float16": {"O1": sorted(BLACK_LIST), "O2": sorted(BLACK_LIST)}}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"
        self.custom_white = set()
        self.custom_black = set()


_state = _AmpState()


def _amp_hook(op_name):
    if not _state.enabled:
        return None
    if op_name in _state.custom_black or op_name in BLACK_LIST:
        return jnp.float32 if _state.level == "O2" else None
    if op_name in _state.custom_white or op_name in WHITE_LIST:
        return _state.dtype
    if _state.level == "O2":
        return _state.dtype
    return None


_dispatch.set_amp_cast_hook(_amp_hook)


@contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    """Mirror paddle.amp.auto_cast.  Default dtype is bfloat16 — the TPU
    native half precision (fp16 also accepted)."""
    prev = (_state.enabled, _state.dtype, _state.level, _state.custom_white,
            _state.custom_black)
    _state.enabled = enable
    _state.dtype = convert_dtype(dtype)
    _state.level = level
    _state.custom_white = set(custom_white_list or ())
    _state.custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        (_state.enabled, _state.dtype, _state.level, _state.custom_white,
         _state.custom_black) = prev


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """Mirror paddle.amp.decorate: O2 casts model params to the AMP dtype and
    turns on optimizer master weights."""
    d = convert_dtype(dtype)
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level == "O2":
        for m in model_list:
            m.to(dtype=d)
    if optimizers is not None:
        single_opt = not isinstance(optimizers, (list, tuple))
        opt_list = [optimizers] if single_opt else list(optimizers)
        for opt in opt_list:
            if master_weight is not False:
                opt._multi_precision = True
        if single_model and single_opt:
            return model_list[0], opt_list[0]
        return model_list if not single_model else model_list[0], opt_list
    return model_list[0] if single_model else model_list


amp_decorate = decorate
