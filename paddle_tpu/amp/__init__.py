"""paddle_tpu.amp — automatic mixed precision (analogue of paddle.amp).

auto_cast installs a per-op dtype policy into core.dispatch (the analogue of
the eager AMP insert in generated ad_funcs, eager_amp_auto_cast.h); the white/
black op lists mirror python/paddle/amp/amp_lists.py.  GradScaler implements
dynamic loss scaling with found-inf short-circuit
(python/paddle/amp/grad_scaler.py:41).
"""

from .auto_cast import auto_cast, amp_guard, decorate, amp_decorate, white_list, black_list
from .grad_scaler import GradScaler, AmpScaler
from . import debugging  # noqa: F401

__all__ = ["auto_cast", "amp_guard", "decorate", "amp_decorate", "GradScaler",
           "AmpScaler", "white_list", "black_list", "debugging"]
