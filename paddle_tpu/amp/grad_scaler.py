"""GradScaler — dynamic loss scaling (analogue of
python/paddle/amp/grad_scaler.py:576 GradScaler / :41 AmpScaler)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["GradScaler", "AmpScaler"]


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 16,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._use_dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._use_dynamic

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list:
            if p._grad is None:
                continue
            g = p._grad._value.astype(jnp.float32) * inv
            found = found or bool(jnp.any(~jnp.isfinite(g)))
            p._grad.set_value(g.astype(p._grad._value.dtype))
        self._found_inf = found

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()

    def update(self):
        if not (self._enable and self._use_dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n_nan_or_inf:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def get_loss_scaling(self):
        return Tensor(jnp.asarray(self._scale, jnp.float32))

    def set_init_loss_scaling(self, value):
        self._scale = float(value)

    def state_dict(self):
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_count": self._good_steps,
            "decr_count": self._bad_steps,
            "use_dynamic_loss_scaling": self._use_dynamic,
        }

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("incr_count", 0)
        self._bad_steps = state.get("decr_count", 0)


AmpScaler = GradScaler
