"""AMP debugging utilities (analogue of python/paddle/amp/debugging.py)."""

from __future__ import annotations

from contextlib import contextmanager

import jax.numpy as jnp

from ..core.flags import set_flags
from ..core.tensor import Tensor

__all__ = ["check_numerics", "enable_tensor_checker", "disable_tensor_checker",
           "collect_operator_stats", "DebugMode"]


class DebugMode:
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 4


def check_numerics(tensor, op_type="", var_name="", debug_mode=None):
    arr = tensor._value if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    num_nan = int(jnp.sum(jnp.isnan(arr)))
    num_inf = int(jnp.sum(jnp.isinf(arr)))
    if num_nan or num_inf:
        raise FloatingPointError(
            f"numerics check failed for {op_type}:{var_name} — "
            f"{num_nan} NaN, {num_inf} Inf values")
    return Tensor(jnp.asarray(num_nan)), Tensor(jnp.asarray(num_inf))


def enable_tensor_checker():
    set_flags({"FLAGS_check_nan_inf": True})


def disable_tensor_checker():
    set_flags({"FLAGS_check_nan_inf": False})


@contextmanager
def collect_operator_stats():
    yield
