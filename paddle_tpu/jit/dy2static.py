"""dy2static: AST conversion of Python control flow for to_static.

Capability analogue of the reference's dy2static transformer stack
(``python/paddle/jit/dy2static/ifelse_transformer.py``,
``loop_transformer.py``, ``convert_operators.py`` — ~20 AST transformers +
the SOT bytecode path).  The TPU-native design is much smaller because the
heavy lifting is done at RUNTIME by :mod:`paddle_tpu.static.control_flow`:

- every ``if``/``while``/``for range()`` statement is rewritten into a call
  to a ``convert_*`` helper, passing the (possibly-undefined) local
  variables the construct reads/writes;
- at runtime the helper checks whether the predicate is a jax tracer: a
  concrete predicate executes the chosen branch directly (exact eager
  semantics, side effects included), a traced predicate lowers to
  ``lax.cond`` / ``lax.while_loop`` via static/control_flow.py;
- constructs the converter cannot express under tracing (break/continue,
  one-sided early returns) are left as plain Python but their predicate is
  wrapped in :func:`assert_not_traced`, which raises a clear error naming
  the construct instead of jax's opaque TracerBoolConversionError.

This mirrors the reference's split between compile-time transformers and
``_jst`` runtime converters (``python/paddle/jit/dy2static/convert_call_func.py``).
"""

from __future__ import annotations

import ast
import functools
import inspect
import textwrap

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

_JST = "__ptpu_jst__"


class Undefined:
    """Placeholder for a local that is not yet bound at the control-flow
    site (reference: dy2static UndefinedVar)."""

    _singleton = None

    def __new__(cls):
        if cls._singleton is None:
            cls._singleton = super().__new__(cls)
        return cls._singleton

    def __repr__(self):
        return "<undefined>"


UNDEFINED = Undefined()


def _unwrap(v):
    return v._value if isinstance(v, Tensor) else v


def _is_tracer(v):
    return isinstance(_unwrap(v), jax.core.Tracer)


# ---------------------------------------------------------------------------
# runtime converters (the _jst namespace inside transformed code)
# ---------------------------------------------------------------------------

def convert_ifelse(pred, true_fn, false_fn, in_values):
    """if/else over possibly-traced predicate.

    true_fn/false_fn take ``in_values`` (current values of the locals the
    branches read) and return the tuple of locals the branches assign.
    """
    if _is_tracer(pred):
        from ..static.control_flow import cond
        return cond(pred, lambda: true_fn(*in_values),
                    lambda: false_fn(*in_values))
    if bool(_unwrap(pred)):
        return true_fn(*in_values)
    return false_fn(*in_values)


def convert_while(cond_fn, body_fn, loop_vars):
    """while over possibly-traced condition; loop_vars is a tuple of the
    locals carried across iterations.  Returns the final loop_vars."""
    first = cond_fn(*loop_vars)
    if _is_tracer(first) or any(_is_tracer(v) for v in loop_vars):
        from ..static.control_flow import while_loop
        out = while_loop(cond_fn, body_fn, list(loop_vars))
        return tuple(out)
    vars_ = tuple(loop_vars)
    cont = bool(_unwrap(first))
    while cont:
        vars_ = tuple(body_fn(*vars_))
        cont = bool(_unwrap(cond_fn(*vars_)))
    return vars_


def convert_logical_and(lhs_fn, rhs_fn):
    l = lhs_fn()
    if _is_tracer(l):
        return Tensor(jnp.logical_and(jnp.asarray(_unwrap(l)).astype(bool),
                                      jnp.asarray(_unwrap(rhs_fn()))
                                      .astype(bool)))
    if not bool(_unwrap(l)):
        return l  # python short-circuit semantics
    return rhs_fn()


def convert_logical_or(lhs_fn, rhs_fn):
    l = lhs_fn()
    if _is_tracer(l):
        return Tensor(jnp.logical_or(jnp.asarray(_unwrap(l)).astype(bool),
                                     jnp.asarray(_unwrap(rhs_fn()))
                                     .astype(bool)))
    if bool(_unwrap(l)):
        return l
    return rhs_fn()


def convert_logical_not(v):
    if _is_tracer(v):
        return Tensor(jnp.logical_not(jnp.asarray(_unwrap(v)).astype(bool)))
    return not bool(_unwrap(v))


def assert_not_traced(pred, construct):
    """Clear trace-time error for constructs dy2static cannot convert."""
    if _is_tracer(pred):
        raise NotImplementedError(
            f"to_static: {construct} cannot be converted to XLA control "
            "flow. Restructure without break/continue/one-sided return, "
            "or compute the predicate outside the traced function. "
            "(reference analogue: dy2static loop/return transformers)")
    return pred


def range_final(i_after, start, stop, step):
    """Post-loop fixup for converted ``for i in range()``: the while form
    leaves i at the first FAILING value; Python leaves it at the last
    YIELDED value (and unbound when the range was empty).  When the bounds
    are concrete the trip count is statically known even if the body traced,
    so exact Python semantics apply; with traced bounds a zero-trip loop
    yields ``start`` (documented deviation — "unbound" has no traced
    representation) instead of the out-of-range ``start - step``."""
    if not (_is_tracer(start) or _is_tracer(stop) or _is_tracer(step)):
        trip = len(range(int(_unwrap(start)), int(_unwrap(stop)),
                         int(_unwrap(step))))
        if trip == 0:
            return UNDEFINED  # zero iterations: Python leaves i unbound
        return i_after - step
    iv = jnp.asarray(_unwrap(i_after))
    sv = jnp.asarray(_unwrap(start))
    out = jnp.where(iv == sv, sv, iv - jnp.asarray(_unwrap(step)))
    return Tensor(out) if isinstance(i_after, Tensor) else out


def range_cond(i, stop, step):
    """Sign-aware range continuation test usable both ways."""
    if _is_tracer(i) or _is_tracer(stop) or _is_tracer(step):
        iv, sv, stv = (jnp.asarray(_unwrap(x)) for x in (i, stop, step))
        return Tensor(jnp.where(stv > 0, iv < sv, iv > sv))
    iv, sv, stv = _unwrap(i), _unwrap(stop), _unwrap(step)
    return iv < sv if stv > 0 else iv > sv


# ---------------------------------------------------------------------------
# AST analysis helpers
# ---------------------------------------------------------------------------

_SCOPE_BARRIERS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                   ast.ClassDef)


def _walk_scope(node):
    """Yield nodes of the statement without descending into nested defs
    (a nested def is yielded but its body — with its own returns, stores,
    loads — belongs to the inner scope and is never entered)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, _SCOPE_BARRIERS):
            continue
        for child in ast.iter_child_nodes(n):
            stack.append(child)


def _names(nodes, ctx_types):
    out = set()
    for root in nodes:
        for n in _walk_scope(root):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ctx_types):
                out.add(n.id)
    return out


def _stores(nodes):
    return _names(nodes, (ast.Store,))


def _loads(nodes):
    return _names(nodes, (ast.Load,))


def _has_node(nodes, kinds):
    for root in nodes:
        for n in _walk_scope(root):
            if isinstance(n, kinds):
                return True
    return False


def _loop_controls_for_body(body):
    """break/continue belonging to THIS loop (not nested loops)."""
    def scan(stmts):
        for s in stmts:
            if isinstance(s, (ast.Break, ast.Continue)):
                return True
            if isinstance(s, (ast.For, ast.While, *_SCOPE_BARRIERS)):
                continue
            for field in ("body", "orelse", "finalbody", "handlers"):
                sub = getattr(s, field, None)
                if sub:
                    if field == "handlers":
                        if any(scan(h.body) for h in sub):
                            return True
                    elif scan(sub):
                        return True
        return False
    return scan(body)


def _ends_with_return(body):
    return bool(body) and isinstance(body[-1], ast.Return)


# ---------------------------------------------------------------------------
# code-construction helpers
# ---------------------------------------------------------------------------

def _name_load(n):
    return ast.Name(id=n, ctx=ast.Load())


def _name_store(n):
    return ast.Name(id=n, ctx=ast.Store())


def _jst_attr(fn_name):
    return ast.Attribute(value=_name_load(_JST), attr=fn_name,
                         ctx=ast.Load())


def _guard_defined(names):
    """try: name \n except (NameError, UnboundLocalError): name = UNDEFINED"""
    stmts = []
    for n in sorted(names):
        stmts.append(ast.Try(
            body=[ast.Expr(value=_name_load(n))],
            handlers=[ast.ExceptHandler(
                type=ast.Tuple(elts=[_name_load("NameError"),
                                     _name_load("UnboundLocalError")],
                               ctx=ast.Load()),
                name=None,
                body=[ast.Assign(targets=[_name_store(n)],
                                 value=_jst_attr("UNDEFINED"))])],
            orelse=[], finalbody=[]))
    return stmts


def _tuple_load(names):
    return ast.Tuple(elts=[_name_load(n) for n in names], ctx=ast.Load())


def _tuple_store(names):
    return ast.Tuple(elts=[_name_store(n) for n in names], ctx=ast.Store())


def _return_tuple(names):
    return ast.Return(value=_tuple_load(names))


# ---------------------------------------------------------------------------
# the transformer
# ---------------------------------------------------------------------------

class _ControlFlowTransformer(ast.NodeTransformer):
    """Rewrites if/while/for statements in one function scope.  Nested
    function defs are left untouched (convert them separately)."""

    def __init__(self, local_names):
        self.locals = set(local_names)
        self.n = 0

    def _uid(self, kind):
        self.n += 1
        return f"__ptpu_{kind}_{self.n}"

    # do not descend into nested scopes
    def visit_FunctionDef(self, node):
        return node

    def visit_AsyncFunctionDef(self, node):
        return node

    def visit_Lambda(self, node):
        return node

    def visit_ClassDef(self, node):
        return node

    def _convert_test(self, test):
        """Convert and/or/not over possibly-traced values inside a
        predicate expression (short-circuit preserved when concrete)."""
        if isinstance(test, ast.BoolOp):
            sub = [self._convert_test(v) for v in test.values]
            fn = ("convert_logical_and" if isinstance(test.op, ast.And)
                  else "convert_logical_or")
            expr = sub[0]
            for rhs in sub[1:]:
                expr = ast.Call(
                    func=_jst_attr(fn),
                    args=[ast.Lambda(args=_empty_args(), body=expr),
                          ast.Lambda(args=_empty_args(), body=rhs)],
                    keywords=[])
            return expr
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return ast.Call(func=_jst_attr("convert_logical_not"),
                            args=[self._convert_test(test.operand)],
                            keywords=[])
        return test

    # ---- if ----------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        branches = node.body + node.orelse
        has_return = _has_node(branches, (ast.Return,))
        test = self._convert_test(node.test)

        if _loop_controls_for_body(branches):
            # break/continue belong to an enclosing loop: hoisting the
            # branch into a function would be a SyntaxError.  Leave the if
            # as Python; the enclosing loop is likewise left unconverted
            # (its body contains the jump), so the predicate guard below
            # gives the clear trace-time error.
            node.test = ast.Call(
                func=_jst_attr("assert_not_traced"),
                args=[test, ast.Constant(
                    value="'if' containing break/continue")],
                keywords=[])
            return node

        if has_return:
            both_return = (_ends_with_return(node.body)
                           and node.orelse and _ends_with_return(node.orelse))
            if not both_return:
                # leave as Python; raise clearly if the pred is traced
                node.test = ast.Call(
                    func=_jst_attr("assert_not_traced"),
                    args=[test, ast.Constant(
                        value="'if' with a one-sided return")],
                    keywords=[])
                return node
            # both branches return: branch fns keep their returns
            in_vars = sorted((_loads(branches) | _loads([node.test]))
                             & self.locals)
            tname, fname = self._uid("true_fn"), self._uid("false_fn")
            t_def = _make_funcdef(tname, in_vars, node.body)
            f_def = _make_funcdef(fname, in_vars, node.orelse)
            call = ast.Call(
                func=_jst_attr("convert_ifelse"),
                args=[test, _name_load(tname), _name_load(fname),
                      _tuple_load(in_vars)],
                keywords=[])
            return (_guard_defined(in_vars) +
                    [t_def, f_def, ast.Return(value=call)])

        stores = sorted(_stores(branches))
        self.locals.update(stores)
        in_vars = sorted(((_loads(branches) | _loads([node.test]))
                          & self.locals) | set(stores))
        out_vars = stores
        if not out_vars:
            # pure side-effect if (e.g. list.append) — run under convert
            # with no outputs
            tname, fname = self._uid("true_fn"), self._uid("false_fn")
            t_def = _make_funcdef(tname, in_vars,
                                  node.body + [_return_tuple([])])
            f_def = _make_funcdef(fname, in_vars,
                                  (node.orelse or [ast.Pass()]) +
                                  [_return_tuple([])])
            call = ast.Call(func=_jst_attr("convert_ifelse"),
                            args=[test, _name_load(tname), _name_load(fname),
                                  _tuple_load(in_vars)],
                            keywords=[])
            return (_guard_defined(in_vars) +
                    [t_def, f_def, ast.Expr(value=call)])

        tname, fname = self._uid("true_fn"), self._uid("false_fn")
        t_def = _make_funcdef(tname, in_vars,
                              node.body + [_return_tuple(out_vars)])
        f_def = _make_funcdef(fname, in_vars,
                              (node.orelse or [ast.Pass()]) +
                              [_return_tuple(out_vars)])
        call = ast.Call(func=_jst_attr("convert_ifelse"),
                        args=[test, _name_load(tname), _name_load(fname),
                              _tuple_load(in_vars)],
                        keywords=[])
        assign = ast.Assign(targets=[_tuple_store(out_vars)], value=call)
        return _guard_defined(set(in_vars) | set(out_vars)) + \
            [t_def, f_def, assign]

    # ---- while -------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        test = self._convert_test(node.test)
        unsupported = (_has_node(node.body, (ast.Return,))
                       or _loop_controls_for_body(node.body)
                       or node.orelse)
        if unsupported:
            node.test = ast.Call(
                func=_jst_attr("assert_not_traced"),
                args=[test, ast.Constant(
                    value="'while' with break/continue/return/else")],
                keywords=[])
            return node

        stores = sorted(_stores(node.body))
        self.locals.update(stores)
        loop_vars = sorted((set(stores) |
                            (_loads([node.test]) & self.locals)))
        cname, bname = self._uid("while_cond"), self._uid("while_body")
        c_def = _make_funcdef(cname, loop_vars, [ast.Return(value=test)])
        b_def = _make_funcdef(bname, loop_vars,
                              node.body + [_return_tuple(loop_vars)])
        call = ast.Call(func=_jst_attr("convert_while"),
                        args=[_name_load(cname), _name_load(bname),
                              _tuple_load(loop_vars)],
                        keywords=[])
        assign = ast.Assign(targets=[_tuple_store(loop_vars)], value=call)
        return _guard_defined(loop_vars) + [c_def, b_def, assign]

    # ---- for range() -------------------------------------------------
    def visit_For(self, node):
        self.generic_visit(node)
        is_range = (isinstance(node.iter, ast.Call)
                    and isinstance(node.iter.func, ast.Name)
                    and node.iter.func.id == "range"
                    and not node.iter.keywords
                    and 1 <= len(node.iter.args) <= 3
                    and isinstance(node.target, ast.Name))
        unsupported = (_has_node(node.body, (ast.Return,))
                       or _loop_controls_for_body(node.body)
                       or node.orelse)
        if not is_range or unsupported:
            return node  # plain python iteration (unrolls under trace)

        args = node.iter.args
        if len(args) == 1:
            start, stop, step = ast.Constant(value=0), args[0], \
                ast.Constant(value=1)
        elif len(args) == 2:
            start, stop, step = args[0], args[1], ast.Constant(value=1)
        else:
            start, stop, step = args

        ivar = node.target.id
        start_v = self._uid("start")
        stop_v = self._uid("stop")
        step_v = self._uid("step")
        self.locals.update({ivar, start_v, stop_v, step_v})
        pre = [ast.Assign(targets=[_name_store(start_v)], value=start),
               ast.Assign(targets=[_name_store(stop_v)], value=stop),
               ast.Assign(targets=[_name_store(step_v)], value=step),
               ast.Assign(targets=[_name_store(ivar)],
                          value=_name_load(start_v))]

        stores = sorted(set(_stores(node.body)) | {ivar})
        self.locals.update(stores)
        loop_vars = sorted(set(stores) | {ivar, stop_v, step_v})
        test = ast.Call(func=_jst_attr("range_cond"),
                        args=[_name_load(ivar), _name_load(stop_v),
                              _name_load(step_v)],
                        keywords=[])
        incr = ast.Assign(
            targets=[_name_store(ivar)],
            value=ast.BinOp(left=_name_load(ivar), op=ast.Add(),
                            right=_name_load(step_v)))
        cname, bname = self._uid("for_cond"), self._uid("for_body")
        c_def = _make_funcdef(cname, loop_vars, [ast.Return(value=test)])
        b_def = _make_funcdef(bname, loop_vars,
                              node.body + [incr, _return_tuple(loop_vars)])
        call = ast.Call(func=_jst_attr("convert_while"),
                        args=[_name_load(cname), _name_load(bname),
                              _tuple_load(loop_vars)],
                        keywords=[])
        assign = ast.Assign(targets=[_tuple_store(loop_vars)], value=call)
        fixup = ast.Assign(
            targets=[_name_store(ivar)],
            value=ast.Call(func=_jst_attr("range_final"),
                           args=[_name_load(ivar), _name_load(start_v),
                                 _name_load(stop_v), _name_load(step_v)],
                           keywords=[]))
        return pre + \
            _guard_defined(set(loop_vars) - {ivar, start_v, stop_v, step_v}) \
            + [c_def, b_def, assign, fixup]


def _empty_args():
    return ast.arguments(posonlyargs=[], args=[], vararg=None,
                         kwonlyargs=[], kw_defaults=[], kwarg=None,
                         defaults=[])


def _make_funcdef(name, argnames, body):
    return ast.FunctionDef(
        name=name,
        args=ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=a, annotation=None) for a in argnames],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[]),
        body=body or [ast.Pass()],
        decorator_list=[],
        returns=None)


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

import weakref

# Keyed on the FUNCTION OBJECT (weakly), not fn.__code__: two closures
# produced by the same factory share one code object but capture different
# cell values, which the conversion bakes into its globals snapshot.
_CONVERT_CACHE = weakref.WeakKeyDictionary()


def _cache_put(fn, converted):
    try:
        _CONVERT_CACHE[fn] = converted
    except TypeError:
        pass


def _needs_conversion(tree):
    return any(isinstance(node, (ast.If, ast.While, ast.For))
               for node in ast.walk(tree))


def convert_to_static(fn):
    """AST-convert a function's Python control flow for tracing.  Returns
    the converted function, or ``fn`` unchanged when there is nothing to
    convert or the source is unavailable (builtins, REPL lambdas)."""
    try:
        cached = _CONVERT_CACHE.get(fn)
    except TypeError:
        cached = None  # non-weakref-able callables (builtins, partials)
    if cached is not None:
        return cached
    try:
        source = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(source)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return fn
    if not isinstance(tree.body[0], (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
        return fn
    func_def = tree.body[0]
    if not _needs_conversion(func_def):
        _cache_put(fn, fn)
        return fn
    func_def.decorator_list = []

    arg_names = {a.arg for a in (func_def.args.posonlyargs +
                                 func_def.args.args +
                                 func_def.args.kwonlyargs)}
    if func_def.args.vararg:
        arg_names.add(func_def.args.vararg.arg)
    if func_def.args.kwarg:
        arg_names.add(func_def.args.kwarg.arg)
    local_names = arg_names | _stores(func_def.body)

    transformer = _ControlFlowTransformer(local_names)
    func_def.body = [transformer.visit(s) for s in func_def.body]
    # flatten lists returned by statement replacements
    def _flatten(stmts):
        out = []
        for s in stmts:
            if isinstance(s, list):
                out.extend(_flatten(s))
            else:
                out.append(s)
        return out
    func_def.body = _flatten(func_def.body)
    ast.fix_missing_locations(tree)

    glb = dict(getattr(fn, "__globals__", {}))
    import sys
    glb[_JST] = sys.modules[__name__]
    if getattr(fn, "__closure__", None):
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                glb[name] = cell.cell_contents
            except ValueError:
                pass
    try:
        code = compile(tree, filename=f"<dy2static:{fn.__name__}>",
                       mode="exec")
        exec(code, glb)
        converted = glb[func_def.name]
    except Exception:
        return fn  # conversion must never break a function that traces fine
    converted = functools.wraps(fn)(converted)
    converted.__ptpu_dy2static__ = True
    _cache_put(fn, converted)
    return converted
