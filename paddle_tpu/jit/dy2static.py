"""dy2static: AST conversion of Python control flow for to_static.

Capability analogue of the reference's dy2static transformer stack
(``python/paddle/jit/dy2static/ifelse_transformer.py``,
``loop_transformer.py``, ``convert_operators.py`` — ~20 AST transformers +
the SOT bytecode path).  The TPU-native design is much smaller because the
heavy lifting is done at RUNTIME by :mod:`paddle_tpu.static.control_flow`:

- every ``if``/``while``/``for range()`` statement is rewritten into a call
  to a ``convert_*`` helper, passing the (possibly-undefined) local
  variables the construct reads/writes;
- at runtime the helper checks whether the predicate is a jax tracer: a
  concrete predicate executes the chosen branch directly (exact eager
  semantics, side effects included), a traced predicate lowers to
  ``lax.cond`` / ``lax.while_loop`` via static/control_flow.py;
- ``break``/``continue`` lower to loop-carried guard booleans before
  conversion (reference ``break_continue_transformer.py:88``): jumps become
  flag assignments, trailing statements get ``if not flag`` guards, the
  loop condition gains ``and not break_flag``, and ``for i in range()``
  loops rewrite to an explicit iterator-variable while form so the loop
  variable lands on the break iteration's value exactly like Python;
- early returns restructure via else-absorption (reference
  ``return_transformer.py:122``): an ``if`` whose branch tail-returns
  absorbs the trailing statements into its other branch, so every path
  tail-returns and the both-branches-return conversion applies;
- the few constructs still inexpressible under tracing (``return`` inside
  a traced loop, jumps inside try/with) are left as plain Python but their
  predicate is wrapped in :func:`assert_not_traced`, which raises a clear
  error naming the construct instead of jax's opaque
  TracerBoolConversionError.

This mirrors the reference's split between compile-time transformers and
``_jst`` runtime converters (``python/paddle/jit/dy2static/convert_call_func.py``).
"""

from __future__ import annotations

import ast
import functools
import inspect
import textwrap

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

_JST = "__ptpu_jst__"


class Undefined:
    """Placeholder for a local that is not yet bound at the control-flow
    site (reference: dy2static UndefinedVar)."""

    _singleton = None

    def __new__(cls):
        if cls._singleton is None:
            cls._singleton = super().__new__(cls)
        return cls._singleton

    def __repr__(self):
        return "<undefined>"


UNDEFINED = Undefined()

# return-value slot sentinel for returns lowered inside traced loops
# (``rv = RET_UNSET`` before the loop; the slot is only read when the
# paired return-flag is True).  A DISTINCT instance from UNDEFINED: only
# this sentinel opts into select-with-zero-fill in convert_ifelse —
# genuinely unbound user locals must keep erroring.
RET_UNSET = Undefined.__new__(type("RetUnset", (Undefined,), {}))


def _unwrap(v):
    return v._value if isinstance(v, Tensor) else v


def _is_tracer(v):
    return isinstance(_unwrap(v), jax.core.Tracer)


# ---------------------------------------------------------------------------
# runtime converters (the _jst namespace inside transformed code)
# ---------------------------------------------------------------------------

def _select_with_unset(pred, true_fn, false_fn, in_values):
    """Traced if/else where a branch may yield the RET_UNSET sentinel
    (a return-value slot not yet assigned): both branches run in the
    current trace (they are pure generated fns) and leaves select
    element-wise, with a sentinel SLOT on one side zero-filled with the
    other side's whole pytree structure (so ``return a, b`` in a loop
    works — the slot adopts the tuple shape).  Validity is tracked by
    the paired return flag, so the zeros are never observed (reference
    analogue: RETURN_NO_VALUE init in return_transformer.py:122)."""
    from ..core.pytree import flatten_tensors, unflatten_tensors
    out_t = true_fn(*in_values)
    out_f = false_fn(*in_values)
    pv = jnp.asarray(_unwrap(pred)).astype(bool).reshape(())

    def select_slot(t, f):
        if t is RET_UNSET and f is RET_UNSET:
            return RET_UNSET
        if t is RET_UNSET:
            t = jax.tree_util.tree_map(
                lambda v: Tensor(jnp.zeros_like(_unwrap(v)))
                if isinstance(v, Tensor) else jnp.zeros_like(v), f,
                is_leaf=lambda v: isinstance(v, Tensor))
        elif f is RET_UNSET:
            f = jax.tree_util.tree_map(
                lambda v: Tensor(jnp.zeros_like(_unwrap(v)))
                if isinstance(v, Tensor) else jnp.zeros_like(v), t,
                is_leaf=lambda v: isinstance(v, Tensor))
        raw_t, td_t, fl_t = flatten_tensors(t)
        raw_f, td_f, fl_f = flatten_tensors(f)
        if td_t != td_f:
            raise ValueError(
                "control flow: branches must return the same pytree "
                f"structure (got {td_t} vs {td_f})")
        leaves = [jnp.where(pv, a, b) for a, b in zip(raw_t, raw_f)]
        flags = [ft or ff for ft, ff in zip(fl_t, fl_f)]
        return unflatten_tensors(leaves, td_t, flags)

    if isinstance(out_t, (tuple, list)) and \
            isinstance(out_f, (tuple, list)) and len(out_t) == len(out_f):
        # locals tuples from generated branch fns: select slot-wise so a
        # RET_UNSET slot can adopt the other side's nested structure
        return tuple(select_slot(t, f) for t, f in zip(out_t, out_f))
    return select_slot(out_t, out_f)


def _contains_unset(values):
    from ..core.pytree import flatten_tensors
    return any(leaf is RET_UNSET
               for leaf in flatten_tensors(tuple(values))[0])


def convert_ifelse(pred, true_fn, false_fn, in_values):
    """if/else over possibly-traced predicate.

    true_fn/false_fn take ``in_values`` (current values of the locals the
    branches read) and return the tuple of locals the branches assign.
    """
    if _is_tracer(pred):
        if _contains_unset(in_values):
            return _select_with_unset(pred, true_fn, false_fn, in_values)
        from ..static.control_flow import cond
        return cond(pred, lambda: true_fn(*in_values),
                    lambda: false_fn(*in_values))
    if bool(_unwrap(pred)):
        return true_fn(*in_values)
    return false_fn(*in_values)


def _zero_like(probe):
    """A zero-valued init matching a probe value's type (for loop carries
    that are assigned before read every iteration).  Tuples (e.g. a
    multi-value return slot) zero element-wise, keeping the structure."""
    if isinstance(probe, (tuple, list)):
        return type(probe)(_zero_like(p) for p in probe)
    if isinstance(probe, Tensor):
        return Tensor(jnp.zeros_like(probe._value))
    if isinstance(probe, bool):
        return False
    if isinstance(probe, (int, float)):
        return type(probe)(0)
    if probe is UNDEFINED or probe is None:
        return probe
    return jnp.zeros_like(jnp.asarray(_unwrap(probe)))


def _traced_while(cond_fn, body_fn, loop_vars):
    from ..static.control_flow import while_loop
    if any(v is UNDEFINED or v is RET_UNSET for v in loop_vars):
        # body-local temps (e.g. a nested loop's iterator/guard flags)
        # are unbound at loop entry but assigned before read every
        # iteration: probe one body evaluation for their types and
        # start them at zero.  A genuine read-before-assign of the
        # unbound local raises inside the probe, as it should.
        # NOTE: the probe runs one extra (traced) body evaluation;
        # functionalized bodies are pure, but a body that mutates
        # closed-over Python state (e.g. list.append) sees one extra
        # call — an accepted trace-time hazard, like jax re-tracing
        probe = body_fn(*loop_vars)
        for v, p in zip(loop_vars, probe):
            if (v is UNDEFINED or v is RET_UNSET) and \
                    (p is UNDEFINED or p is RET_UNSET):
                # e.g. a local only assigned under a traced conditional:
                # one body evaluation cannot determine its type, and
                # lax.while_loop would fail on the sentinel with an
                # opaque structure error — raise the clear message here.
                raise NotImplementedError(
                    "dy2static: a variable carried by a traced while "
                    "loop is unbound at loop entry and still unbound "
                    "after one loop iteration (it is only assigned "
                    "under a traced conditional). Initialize it before "
                    "the loop.")
        loop_vars = tuple(
            _zero_like(p) if (v is UNDEFINED or v is RET_UNSET) else v
            for v, p in zip(loop_vars, probe))
    out = while_loop(cond_fn, body_fn, list(loop_vars))
    return tuple(out)


def convert_while(cond_fn, body_fn, loop_vars):
    """while over possibly-traced condition; loop_vars is a tuple of the
    locals carried across iterations.  Returns the final loop_vars.

    Tracedness follows the CONDITION: a concrete condition runs the loop
    eagerly (which unrolls under an outer trace — traced loop vars flow
    through fine, and python-only body ops like list indexing keep
    working); the moment the condition becomes traced, the remaining
    iterations lower to lax.while_loop from the current state."""
    first = cond_fn(*loop_vars)
    if _is_tracer(first):
        return _traced_while(cond_fn, body_fn, loop_vars)
    vars_ = tuple(loop_vars)
    cont = bool(_unwrap(first))
    while cont:
        vars_ = tuple(body_fn(*vars_))
        nxt = cond_fn(*vars_)
        if _is_tracer(nxt):
            return _traced_while(cond_fn, body_fn, vars_)
        cont = bool(_unwrap(nxt))
    return vars_


def convert_logical_and(lhs_fn, rhs_fn):
    l = lhs_fn()
    if _is_tracer(l):
        return Tensor(jnp.logical_and(jnp.asarray(_unwrap(l)).astype(bool),
                                      jnp.asarray(_unwrap(rhs_fn()))
                                      .astype(bool)))
    if not bool(_unwrap(l)):
        return l  # python short-circuit semantics
    return rhs_fn()


def convert_logical_or(lhs_fn, rhs_fn):
    l = lhs_fn()
    if _is_tracer(l):
        return Tensor(jnp.logical_or(jnp.asarray(_unwrap(l)).astype(bool),
                                     jnp.asarray(_unwrap(rhs_fn()))
                                     .astype(bool)))
    if bool(_unwrap(l)):
        return l
    return rhs_fn()


def convert_logical_not(v):
    if _is_tracer(v):
        return Tensor(jnp.logical_not(jnp.asarray(_unwrap(v)).astype(bool)))
    return not bool(_unwrap(v))


def concrete_true(v):
    """True only for a CONCRETELY truthy value — traced values yield False
    (used by lowered non-range for loops to execute a real ``break`` when
    the guard flag is concrete)."""
    return (not _is_tracer(v)) and bool(_unwrap(v))


def assert_not_traced(pred, construct):
    """Clear trace-time error for constructs dy2static cannot convert."""
    if _is_tracer(pred):
        raise NotImplementedError(
            f"to_static: {construct} cannot be converted to XLA control "
            "flow (break/continue and one-sided returns ARE converted; "
            "the remaining unsupported forms are `return` inside a traced "
            "loop and jumps inside try/with). Hoist the return out of the "
            "loop or compute the predicate outside the traced function. "
            "(reference analogue: dy2static loop/return transformers)")
    return pred


def range_final(i_after, start, stop, step):
    """Post-loop fixup for converted ``for i in range()``: the while form
    leaves i at the first FAILING value; Python leaves it at the last
    YIELDED value (and unbound when the range was empty).  When the bounds
    are concrete the trip count is statically known even if the body traced,
    so exact Python semantics apply; with traced bounds a zero-trip loop
    yields ``start`` (documented deviation — "unbound" has no traced
    representation) instead of the out-of-range ``start - step``."""
    if not (_is_tracer(start) or _is_tracer(stop) or _is_tracer(step)):
        trip = len(range(int(_unwrap(start)), int(_unwrap(stop)),
                         int(_unwrap(step))))
        if trip == 0:
            return UNDEFINED  # zero iterations: Python leaves i unbound
        return i_after - step
    iv = jnp.asarray(_unwrap(i_after))
    sv = jnp.asarray(_unwrap(start))
    out = jnp.where(iv == sv, sv, iv - jnp.asarray(_unwrap(step)))
    return Tensor(out) if isinstance(i_after, Tensor) else out


def range_cond(i, stop, step):
    """Sign-aware range continuation test usable both ways."""
    if _is_tracer(i) or _is_tracer(stop) or _is_tracer(step):
        iv, sv, stv = (jnp.asarray(_unwrap(x)) for x in (i, stop, step))
        return Tensor(jnp.where(stv > 0, iv < sv, iv > sv))
    iv, sv, stv = _unwrap(i), _unwrap(stop), _unwrap(step)
    return iv < sv if stv > 0 else iv > sv


# ---------------------------------------------------------------------------
# AST analysis helpers
# ---------------------------------------------------------------------------

_SCOPE_BARRIERS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                   ast.ClassDef)


def _walk_scope(node):
    """Yield nodes of the statement without descending into nested defs
    (a nested def is yielded but its body — with its own returns, stores,
    loads — belongs to the inner scope and is never entered)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, _SCOPE_BARRIERS):
            continue
        for child in ast.iter_child_nodes(n):
            stack.append(child)


def _names(nodes, ctx_types):
    out = set()
    for root in nodes:
        for n in _walk_scope(root):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ctx_types):
                out.add(n.id)
    return out


def _stores(nodes):
    return _names(nodes, (ast.Store,))


def _loads(nodes):
    return _names(nodes, (ast.Load,))


def _has_node(nodes, kinds):
    for root in nodes:
        for n in _walk_scope(root):
            if isinstance(n, kinds):
                return True
    return False


def _scan_loop_jumps(body, kinds, only_guarded=False):
    """True when a statement of ``kinds`` belonging to THIS loop level
    occurs in ``body`` (nested loops keep their own jumps; nested defs are
    barriers).  ``only_guarded=True`` matches only occurrences inside
    try/with — the forms the guard-flag lowering cannot express."""
    def scan(stmts, guarded):
        for s in stmts:
            if isinstance(s, kinds) and (guarded or not only_guarded):
                return True
            if isinstance(s, (ast.For, ast.While, *_SCOPE_BARRIERS)):
                continue
            g = guarded or isinstance(s, (ast.Try, ast.With))
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(s, field, None)
                if sub and scan(sub, g):
                    return True
            for h in getattr(s, "handlers", []) or []:
                if scan(h.body, g):
                    return True
        return False
    return scan(body, False)


def _loop_controls_for_body(body):
    """break/continue belonging to THIS loop (not nested loops)."""
    return _scan_loop_jumps(body, (ast.Break, ast.Continue))


def _ends_with_return(body):
    return bool(body) and isinstance(body[-1], ast.Return)


def _parse_range_for(node):
    """(start, stop, step) AST nodes when ``node`` is ``for <Name> in
    range(...)`` with 1-3 positional args, else None."""
    if not (isinstance(node.iter, ast.Call)
            and isinstance(node.iter.func, ast.Name)
            and node.iter.func.id == "range"
            and not node.iter.keywords
            and 1 <= len(node.iter.args) <= 3
            and isinstance(node.target, ast.Name)):
        return None
    args = node.iter.args
    if len(args) == 1:
        return ast.Constant(value=0), args[0], ast.Constant(value=1)
    if len(args) == 2:
        return args[0], args[1], ast.Constant(value=1)
    return args[0], args[1], args[2]


# ---------------------------------------------------------------------------
# code-construction helpers
# ---------------------------------------------------------------------------

def _name_load(n):
    return ast.Name(id=n, ctx=ast.Load())


def _name_store(n):
    return ast.Name(id=n, ctx=ast.Store())


def _jst_attr(fn_name):
    return ast.Attribute(value=_name_load(_JST), attr=fn_name,
                         ctx=ast.Load())


def _guard_defined(names):
    """try: name \n except (NameError, UnboundLocalError): name = UNDEFINED"""
    stmts = []
    for n in sorted(names):
        stmts.append(ast.Try(
            body=[ast.Expr(value=_name_load(n))],
            handlers=[ast.ExceptHandler(
                type=ast.Tuple(elts=[_name_load("NameError"),
                                     _name_load("UnboundLocalError")],
                               ctx=ast.Load()),
                name=None,
                body=[ast.Assign(targets=[_name_store(n)],
                                 value=_jst_attr("UNDEFINED"))])],
            orelse=[], finalbody=[]))
    return stmts


def _tuple_load(names):
    return ast.Tuple(elts=[_name_load(n) for n in names], ctx=ast.Load())


def _tuple_store(names):
    return ast.Tuple(elts=[_name_store(n) for n in names], ctx=ast.Store())


def _return_tuple(names):
    return ast.Return(value=_tuple_load(names))


# ---------------------------------------------------------------------------
# pass 1: early-return restructuring (else-absorption)
# ---------------------------------------------------------------------------

def _all_paths_return(stmts):
    """Deep tail check: every execution path through this block ends in a
    Return (an If counts when both branches do)."""
    if not stmts:
        return False
    last = stmts[-1]
    if isinstance(last, ast.Return):
        return True
    if isinstance(last, ast.If):
        return bool(last.orelse) and _all_paths_return(last.body) \
            and _all_paths_return(last.orelse)
    return False


def _restructure_returns(stmts):
    """Rewrite a block so every Return sits in tail position: an If whose
    branch tail-returns absorbs the trailing statements into the other
    branch (reference return/early_return transformers).  Dead code after
    a Return is dropped.  Does not descend into loops or nested defs —
    loop-internal returns keep the assert_not_traced fallback."""
    out = []
    for i, s in enumerate(stmts):
        rest = stmts[i + 1:]
        if isinstance(s, ast.Return):
            out.append(s)
            return out  # rest is unreachable
        if isinstance(s, ast.If) and _has_node([s], (ast.Return,)):
            body = _restructure_returns(s.body)
            orelse = _restructure_returns(s.orelse) if s.orelse else []
            b_ret, o_ret = _all_paths_return(body), _all_paths_return(orelse)
            if b_ret and o_ret:
                out.append(ast.If(test=s.test, body=body, orelse=orelse))
                return out  # rest unreachable
            if b_ret and rest:
                out.append(ast.If(
                    test=s.test, body=body,
                    orelse=_restructure_returns(orelse + rest)))
                return out
            if o_ret and rest:
                out.append(ast.If(
                    test=s.test, body=_restructure_returns(body + rest),
                    orelse=orelse))
                return out
            out.append(ast.If(test=s.test, body=body, orelse=orelse))
            continue
        out.append(s)
    return out


def _lower_returns(func_def):
    """Normalize ``func_def.body`` so all returns are tail-position.  Adds
    an explicit ``return None`` for the implicit fall-through when the
    function mixes returning and non-returning paths."""
    body = func_def.body
    if not _has_node(body, (ast.Return,)):
        return
    restructured = _restructure_returns(body)
    if not _all_paths_return(restructured):
        restructured = _restructure_returns(
            restructured + [ast.Return(value=ast.Constant(value=None))])
    func_def.body = restructured


# ---------------------------------------------------------------------------
# pass 0: return-inside-loop lowering (return flag + value slot)
# ---------------------------------------------------------------------------

class _ReturnInLoopLowering(ast.NodeTransformer):
    """Lowers ``return <expr>`` inside loops into flag dataflow the later
    passes can convert (reference
    ``python/paddle/jit/dy2static/return_transformer.py:122`` — their
    RETURN_NO_VALUE init plays the role of our RET_UNSET sentinel):

        __ret_flag = False          # before the loop
        __ret_val  = _jst.RET_UNSET
        for/while ...:
            ... __ret_flag = True; __ret_val = expr; break ...
        if __ret_flag:
            return __ret_val        # pass 1 else-absorbs; pass 2 lowers
                                    # the injected break

    One flag/value pair per function; nested loops compose because the
    inner loop's synthesized post-loop ``if __ret_flag: return __ret_val``
    is itself a return inside the outer loop, which the outer visit
    lowers to ``if __ret_flag: __ret_flag = True; ... break`` — i.e. a
    plain flag-break cascade.  Bare ``return`` (no value) keeps the
    existing clear trace-time error path.  Returns inside a loop's
    ``else`` clause are function-scope (they run after the loop) and are
    left to passes 1/2.
    """

    def __init__(self):
        self.flag = "__ptpu_ret_flag"
        self.val = "__ptpu_ret_val"
        self.used = False

    def visit_FunctionDef(self, node):
        return node

    def visit_AsyncFunctionDef(self, node):
        return node

    def visit_Lambda(self, node):
        return node

    def visit_ClassDef(self, node):
        return node

    def _replace_returns(self, stmts):
        """Replace value-returns in this statement list (descending into
        If branches; loops at this depth were already visited bottom-up
        and contain no returns).  Returns None when a bare return is
        found (caller leaves the loop unlowered)."""
        out = []
        for s in stmts:
            if isinstance(s, ast.Return):
                if s.value is None:
                    return None
                out.append(ast.Assign(targets=[_name_store(self.flag)],
                                      value=ast.Constant(value=True)))
                out.append(ast.Assign(targets=[_name_store(self.val)],
                                      value=s.value))
                out.append(ast.Break())
                return out  # rest unreachable
            if isinstance(s, ast.If):
                body = self._replace_returns(s.body)
                orelse = self._replace_returns(s.orelse)
                if body is None or orelse is None:
                    return None
                out.append(ast.If(test=s.test, body=body or [ast.Pass()],
                                  orelse=orelse))
                continue
            out.append(s)
        return out

    def _lower_loop(self, node):
        self.generic_visit(node)  # inner loops first (bottom-up)
        if not _has_node(node.body, (ast.Return,)):
            return node
        new_body = self._replace_returns(node.body)
        if new_body is None:
            return node  # bare return: keep the clear fallback error
        if _has_node(new_body, (ast.Return,)):
            # A return survived the walk (nested in try/with, which
            # _replace_returns does not descend into and pass 2 cannot
            # lower anyway) — leave the loop untouched so the generic
            # return-in-loop error path fires instead of injecting dead
            # flag plumbing around a half-lowered loop.
            return node
        node.body = new_body or [ast.Pass()]
        self.used = True
        init = [ast.Assign(targets=[_name_store(self.flag)],
                           value=ast.Constant(value=False)),
                ast.Assign(targets=[_name_store(self.val)],
                           value=_jst_attr("RET_UNSET"))]
        post = ast.If(test=_name_load(self.flag),
                      body=[ast.Return(value=_name_load(self.val))],
                      orelse=[])
        return init + [node, post]

    visit_While = _lower_loop
    visit_For = _lower_loop


# ---------------------------------------------------------------------------
# pass 2: break/continue lowering (guard-flag dataflow)
# ---------------------------------------------------------------------------

class _JumpLowering(ast.NodeTransformer):
    """Rewrites loops containing break/continue (or an else clause) into
    guard-flag form with no jump statements (reference
    break_continue_transformer.py:88):

    - ``break`` -> ``flag = True``; trailing statements of every enclosing
      block up to the loop get an ``if not flag`` guard; the loop condition
      gains ``and not flag``;
    - ``continue`` -> same with a per-iteration flag reset at body top;
    - ``for i in range(...)`` rewrites to an explicit iterator-variable
      while loop (i assigned from the iterator at body top, so after a
      break ``i`` holds the break iteration's value exactly like Python);
    - non-range ``for`` keeps its header and guards the whole body with
      ``if not break_flag`` (iterations after a break are no-ops);
    - ``while``/``for`` ``else`` clauses run under ``if not break_flag``.

    Loops whose jumps sit inside try/with, or that contain ``return``, are
    left untouched (assert_not_traced fallback)."""

    def __init__(self):
        self.n = 0

    def _fresh(self, kind):
        self.n += 1
        return f"__ptpu_low_{kind}_{self.n}"

    def visit_FunctionDef(self, node):
        return node

    def visit_AsyncFunctionDef(self, node):
        return node

    def visit_Lambda(self, node):
        return node

    def visit_ClassDef(self, node):
        return node

    def _jumps_unlowerable(self, body):
        """Jumps inside try/with (this loop's jumps only) can't be
        guard-lowered."""
        return _scan_loop_jumps(body, (ast.Break, ast.Continue),
                                only_guarded=True)

    def _lower_block(self, stmts, brk, cont, on_jump=None):
        """on_jump: nullary callable returning fresh statements inserted
        before each break/continue flag set (the for-loop shadow capture
        — Python's post-loop loop-variable value is its value AT the jump
        site, body mutations included)."""
        out = []
        for i, s in enumerate(stmts):
            rest = stmts[i + 1:]
            if isinstance(s, ast.Break):
                if on_jump is not None:
                    out.extend(on_jump())
                out.append(ast.Assign(targets=[_name_store(brk)],
                                      value=ast.Constant(value=True)))
                return out  # rest unreachable
            if isinstance(s, ast.Continue):
                if on_jump is not None:
                    out.extend(on_jump())
                out.append(ast.Assign(targets=[_name_store(cont)],
                                      value=ast.Constant(value=True)))
                return out
            if isinstance(s, ast.If) and _loop_controls_for_body([s]):
                new_if = ast.If(
                    test=s.test,
                    body=self._lower_block(s.body, brk, cont, on_jump)
                    or [ast.Pass()],
                    orelse=self._lower_block(s.orelse, brk, cont, on_jump))
                out.append(new_if)
                if rest:
                    flags = [_name_load(brk)]
                    if cont is not None:
                        flags.append(_name_load(cont))
                    guard = ast.UnaryOp(
                        op=ast.Not(),
                        operand=(flags[0] if len(flags) == 1 else
                                 ast.BoolOp(op=ast.Or(), values=flags)))
                    out.append(ast.If(
                        test=guard,
                        body=self._lower_block(rest, brk, cont, on_jump) or
                        [ast.Pass()],
                        orelse=[]))
                return out
            out.append(s)
        return out

    def _loop_prep(self, node):
        """Common gating + flag allocation.  Returns None when the loop
        must stay untouched."""
        has_jumps = _loop_controls_for_body(node.body)
        if not has_jumps and not node.orelse:
            return None
        if _has_node(node.body, (ast.Return,)) or \
                self._jumps_unlowerable(node.body):
            return None
        brk = self._fresh("brk")
        has_cont = self._has_continue(node.body)
        cont = self._fresh("cont") if has_cont else None
        return brk, cont

    @staticmethod
    def _has_continue(body):
        return _scan_loop_jumps(body, (ast.Continue,))

    def _finish(self, out, node, brk):
        if node.orelse:
            out.append(ast.If(
                test=ast.UnaryOp(op=ast.Not(), operand=_name_load(brk)),
                body=node.orelse, orelse=[]))
        return out

    def visit_While(self, node):
        self.generic_visit(node)  # inner loops first
        prep = self._loop_prep(node)
        if prep is None:
            return node
        brk, cont = prep
        body = ([ast.Assign(targets=[_name_store(cont)],
                            value=ast.Constant(value=False))]
                if cont else [])
        body += self._lower_block(node.body, brk, cont) or [ast.Pass()]
        # flag first: after a break the original condition must not be
        # re-evaluated (it may crash or repeat side effects)
        test = ast.BoolOp(op=ast.And(), values=[
            ast.UnaryOp(op=ast.Not(), operand=_name_load(brk)),
            node.test])
        out = [ast.Assign(targets=[_name_store(f)],
                          value=ast.Constant(value=False))
               for f in ([brk] + ([cont] if cont else []))]
        out.append(ast.While(test=test, body=body, orelse=[]))
        return self._finish(out, node, brk)

    def visit_For(self, node):
        self.generic_visit(node)
        prep = self._loop_prep(node)
        if prep is None:
            return node
        brk, cont = prep
        reset = ([ast.Assign(targets=[_name_store(cont)],
                             value=ast.Constant(value=False))]
                 if cont else [])
        init_brk = [ast.Assign(targets=[_name_store(f)],
                               value=ast.Constant(value=False))
                    for f in ([brk] + ([cont] if cont else []))]

        rng = _parse_range_for(node)
        if rng is None:
            # keep the iterator and guard the body; a REAL break fires when
            # the flag is concretely True (stops consuming the iterator —
            # critical for infinite/shared generators), while a traced flag
            # leaves concrete_true False and the finite iterator unrolls
            # with a no-op guarded body.  Shadows track the loop variables
            # (every Store-context Name in the target, so tuple-unpacking
            # works; subscript/attribute targets read their base/index —
            # Load ctx — and get no shadow) so post-loop reads see what
            # Python sees: the value AT the jump site (body mutations
            # included — capture runs at each break/continue and at the
            # end of an un-jumped iteration, while the For header keeps
            # rebinding the target on the no-op post-break iterations).
            tgt_names = [n.id for n in ast.walk(node.target)
                         if isinstance(n, ast.Name)
                         and isinstance(n.ctx, ast.Store)]
            shadows = [(nm, self._fresh("item")) for nm in tgt_names]

            def capture():
                return [ast.Assign(targets=[_name_store(sh)],
                                   value=_name_load(nm))
                        for nm, sh in shadows]
            # top capture keeps the shadow bound on every active
            # iteration (a jump-site capture alone would be branch-local
            # inside a traced conditional — UNDEFINED on the other arm);
            # the end/jump-site captures overwrite it with the value at
            # the jump site so body mutations are kept
            guarded = capture() + (self._lower_block(
                list(node.body) + capture(), brk, cont,
                on_jump=capture) or [ast.Pass()])
            body = reset + [
                ast.If(test=ast.UnaryOp(op=ast.Not(),
                                        operand=_name_load(brk)),
                       body=guarded, orelse=[]),
                ast.If(test=ast.Call(func=_jst_attr("concrete_true"),
                                     args=[_name_load(brk)], keywords=[]),
                       body=[ast.Break()], orelse=[]),
            ]
            out = init_brk + [
                ast.For(target=node.target, iter=node.iter, body=body,
                        orelse=[])]
            if shadows:
                # zero-trip loops leave both names unbound: restore the
                # targets from the shadows only when the shadows exist
                out.append(ast.Try(
                    body=[ast.Assign(targets=[_name_store(nm)],
                                     value=_name_load(sh))
                          for nm, sh in shadows],
                    handlers=[ast.ExceptHandler(
                        type=ast.Tuple(elts=[_name_load("NameError"),
                                             _name_load("UnboundLocalError")],
                                       ctx=ast.Load()),
                        name=None, body=[ast.Pass()])],
                    orelse=[], finalbody=[]))
            return self._finish(out, node, brk)

        start, stop, step = rng
        lowered = self._lower_block(node.body, brk, cont) or [ast.Pass()]
        ivar = node.target.id
        itv, stopv, stepv = (self._fresh("it"), self._fresh("stop"),
                             self._fresh("step"))
        pre = [ast.Assign(targets=[_name_store(itv)], value=start),
               ast.Assign(targets=[_name_store(stopv)], value=stop),
               ast.Assign(targets=[_name_store(stepv)], value=step),
               # pre-bind the loop var so traced zero-trip loops have a
               # carried value (post-zero-trip reads see start — documented
               # deviation from Python's unbound)
               ast.Assign(targets=[_name_store(ivar)],
                          value=_name_load(itv))] + init_brk
        test = ast.BoolOp(op=ast.And(), values=[
            ast.Call(func=_jst_attr("range_cond"),
                     args=[_name_load(itv), _name_load(stopv),
                           _name_load(stepv)], keywords=[]),
            ast.UnaryOp(op=ast.Not(), operand=_name_load(brk))])
        body = reset + [
            ast.Assign(targets=[_name_store(ivar)], value=_name_load(itv)),
            ast.Assign(targets=[_name_store(itv)],
                       value=ast.BinOp(left=_name_load(itv), op=ast.Add(),
                                       right=_name_load(stepv))),
        ] + lowered
        out = pre + [ast.While(test=test, body=body, orelse=[])]
        return self._finish(out, node, brk)


# ---------------------------------------------------------------------------
# the transformer
# ---------------------------------------------------------------------------

class _ControlFlowTransformer(ast.NodeTransformer):
    """Rewrites if/while/for statements in one function scope.  Nested
    function defs are left untouched (convert them separately)."""

    def __init__(self, local_names):
        self.locals = set(local_names)
        self.n = 0

    def _uid(self, kind):
        self.n += 1
        return f"__ptpu_{kind}_{self.n}"

    # do not descend into nested scopes
    def visit_FunctionDef(self, node):
        return node

    def visit_AsyncFunctionDef(self, node):
        return node

    def visit_Lambda(self, node):
        return node

    def visit_ClassDef(self, node):
        return node

    def _convert_test(self, test):
        """Convert and/or/not over possibly-traced values inside a
        predicate expression (short-circuit preserved when concrete)."""
        if isinstance(test, ast.BoolOp):
            sub = [self._convert_test(v) for v in test.values]
            fn = ("convert_logical_and" if isinstance(test.op, ast.And)
                  else "convert_logical_or")
            expr = sub[0]
            for rhs in sub[1:]:
                expr = ast.Call(
                    func=_jst_attr(fn),
                    args=[ast.Lambda(args=_empty_args(), body=expr),
                          ast.Lambda(args=_empty_args(), body=rhs)],
                    keywords=[])
            return expr
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return ast.Call(func=_jst_attr("convert_logical_not"),
                            args=[self._convert_test(test.operand)],
                            keywords=[])
        return test

    # ---- if ----------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        branches = node.body + node.orelse
        has_return = _has_node(branches, (ast.Return,))
        test = self._convert_test(node.test)

        if _loop_controls_for_body(branches):
            # break/continue belong to an enclosing loop: hoisting the
            # branch into a function would be a SyntaxError.  Leave the if
            # as Python; the enclosing loop is likewise left unconverted
            # (its body contains the jump), so the predicate guard below
            # gives the clear trace-time error.
            node.test = ast.Call(
                func=_jst_attr("assert_not_traced"),
                args=[test, ast.Constant(
                    value="'if' containing break/continue")],
                keywords=[])
            return node

        if has_return:
            both_return = (_ends_with_return(node.body)
                           and node.orelse and _ends_with_return(node.orelse))
            if not both_return:
                # leave as Python; raise clearly if the pred is traced
                node.test = ast.Call(
                    func=_jst_attr("assert_not_traced"),
                    args=[test, ast.Constant(
                        value="'if' with a one-sided return")],
                    keywords=[])
                return node
            # both branches return: branch fns keep their returns
            in_vars = sorted((_loads(branches) | _loads([node.test]))
                             & self.locals)
            tname, fname = self._uid("true_fn"), self._uid("false_fn")
            t_def = _make_funcdef(tname, in_vars, node.body)
            f_def = _make_funcdef(fname, in_vars, node.orelse)
            call = ast.Call(
                func=_jst_attr("convert_ifelse"),
                args=[test, _name_load(tname), _name_load(fname),
                      _tuple_load(in_vars)],
                keywords=[])
            return (_guard_defined(in_vars) +
                    [t_def, f_def, ast.Return(value=call)])

        stores = sorted(_stores(branches))
        self.locals.update(stores)
        in_vars = sorted(((_loads(branches) | _loads([node.test]))
                          & self.locals) | set(stores))
        out_vars = stores
        if not out_vars:
            # pure side-effect if (e.g. list.append) — run under convert
            # with no outputs
            tname, fname = self._uid("true_fn"), self._uid("false_fn")
            t_def = _make_funcdef(tname, in_vars,
                                  node.body + [_return_tuple([])])
            f_def = _make_funcdef(fname, in_vars,
                                  (node.orelse or [ast.Pass()]) +
                                  [_return_tuple([])])
            call = ast.Call(func=_jst_attr("convert_ifelse"),
                            args=[test, _name_load(tname), _name_load(fname),
                                  _tuple_load(in_vars)],
                            keywords=[])
            return (_guard_defined(in_vars) +
                    [t_def, f_def, ast.Expr(value=call)])

        tname, fname = self._uid("true_fn"), self._uid("false_fn")
        t_def = _make_funcdef(tname, in_vars,
                              node.body + [_return_tuple(out_vars)])
        f_def = _make_funcdef(fname, in_vars,
                              (node.orelse or [ast.Pass()]) +
                              [_return_tuple(out_vars)])
        call = ast.Call(func=_jst_attr("convert_ifelse"),
                        args=[test, _name_load(tname), _name_load(fname),
                              _tuple_load(in_vars)],
                        keywords=[])
        assign = ast.Assign(targets=[_tuple_store(out_vars)], value=call)
        return _guard_defined(set(in_vars) | set(out_vars)) + \
            [t_def, f_def, assign]

    # ---- while -------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        test = self._convert_test(node.test)
        unsupported = (_has_node(node.body, (ast.Return,))
                       or _loop_controls_for_body(node.body)
                       or node.orelse)
        if unsupported:
            node.test = ast.Call(
                func=_jst_attr("assert_not_traced"),
                args=[test, ast.Constant(
                    value="'while' with break/continue/return/else")],
                keywords=[])
            return node

        stores = sorted(_stores(node.body))
        self.locals.update(stores)
        loop_vars = sorted((set(stores) |
                            (_loads([node.test]) & self.locals)))
        cname, bname = self._uid("while_cond"), self._uid("while_body")
        c_def = _make_funcdef(cname, loop_vars, [ast.Return(value=test)])
        b_def = _make_funcdef(bname, loop_vars,
                              node.body + [_return_tuple(loop_vars)])
        call = ast.Call(func=_jst_attr("convert_while"),
                        args=[_name_load(cname), _name_load(bname),
                              _tuple_load(loop_vars)],
                        keywords=[])
        assign = ast.Assign(targets=[_tuple_store(loop_vars)], value=call)
        return _guard_defined(loop_vars) + [c_def, b_def, assign]

    # ---- for range() -------------------------------------------------
    def visit_For(self, node):
        self.generic_visit(node)
        rng = _parse_range_for(node)
        unsupported = (_has_node(node.body, (ast.Return,))
                       or _loop_controls_for_body(node.body)
                       or node.orelse)
        if rng is None or unsupported:
            return node  # plain python iteration (unrolls under trace)

        start, stop, step = rng
        ivar = node.target.id
        start_v = self._uid("start")
        stop_v = self._uid("stop")
        step_v = self._uid("step")
        self.locals.update({ivar, start_v, stop_v, step_v})
        pre = [ast.Assign(targets=[_name_store(start_v)], value=start),
               ast.Assign(targets=[_name_store(stop_v)], value=stop),
               ast.Assign(targets=[_name_store(step_v)], value=step),
               ast.Assign(targets=[_name_store(ivar)],
                          value=_name_load(start_v))]

        stores = sorted(set(_stores(node.body)) | {ivar})
        self.locals.update(stores)
        loop_vars = sorted(set(stores) | {ivar, stop_v, step_v})
        test = ast.Call(func=_jst_attr("range_cond"),
                        args=[_name_load(ivar), _name_load(stop_v),
                              _name_load(step_v)],
                        keywords=[])
        incr = ast.Assign(
            targets=[_name_store(ivar)],
            value=ast.BinOp(left=_name_load(ivar), op=ast.Add(),
                            right=_name_load(step_v)))
        cname, bname = self._uid("for_cond"), self._uid("for_body")
        c_def = _make_funcdef(cname, loop_vars, [ast.Return(value=test)])
        b_def = _make_funcdef(bname, loop_vars,
                              node.body + [incr, _return_tuple(loop_vars)])
        call = ast.Call(func=_jst_attr("convert_while"),
                        args=[_name_load(cname), _name_load(bname),
                              _tuple_load(loop_vars)],
                        keywords=[])
        assign = ast.Assign(targets=[_tuple_store(loop_vars)], value=call)
        fixup = ast.Assign(
            targets=[_name_store(ivar)],
            value=ast.Call(func=_jst_attr("range_final"),
                           args=[_name_load(ivar), _name_load(start_v),
                                 _name_load(stop_v), _name_load(step_v)],
                           keywords=[]))
        return pre + \
            _guard_defined(set(loop_vars) - {ivar, start_v, stop_v, step_v}) \
            + [c_def, b_def, assign, fixup]


def _empty_args():
    return ast.arguments(posonlyargs=[], args=[], vararg=None,
                         kwonlyargs=[], kw_defaults=[], kwarg=None,
                         defaults=[])


def _make_funcdef(name, argnames, body):
    return ast.FunctionDef(
        name=name,
        args=ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=a, annotation=None) for a in argnames],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[]),
        body=body or [ast.Pass()],
        decorator_list=[],
        returns=None)


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

import weakref

# Keyed on the FUNCTION OBJECT (weakly), not fn.__code__: two closures
# produced by the same factory share one code object but capture different
# cell values, which the conversion bakes into its globals snapshot.
_CONVERT_CACHE = weakref.WeakKeyDictionary()


def _cache_put(fn, converted):
    try:
        _CONVERT_CACHE[fn] = converted
    except TypeError:
        pass


def _needs_conversion(tree):
    return any(isinstance(node, (ast.If, ast.While, ast.For))
               for node in ast.walk(tree))


def convert_to_static(fn):
    """AST-convert a function's Python control flow for tracing.  Returns
    the converted function, or ``fn`` unchanged when there is nothing to
    convert or the source is unavailable (builtins, REPL lambdas)."""
    try:
        cached = _CONVERT_CACHE.get(fn)
    except TypeError:
        cached = None  # non-weakref-able callables (builtins, partials)
    if cached is not None:
        return cached
    code = getattr(fn, "__code__", None)
    if code is not None and "__class__" in code.co_freevars:
        # zero-arg super() needs the compiler-provided __class__ cell,
        # which a module-level recompile cannot reproduce — leave the
        # function unconverted rather than break it at call time
        return fn
    try:
        source = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(source)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return fn
    if not isinstance(tree.body[0], (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
        return fn
    func_def = tree.body[0]
    if not _needs_conversion(func_def):
        _cache_put(fn, fn)
        return fn
    func_def.decorator_list = []

    # pass 0: returns inside loops -> flag/value slots + break (must run
    # first so pass 1 sees the synthesized post-loop return-if and pass 2
    # sees the injected break).
    ril = _ReturnInLoopLowering()
    ril_body = []
    for s in func_def.body:
        r = ril.visit(s)
        ril_body.extend(r if isinstance(r, list) else [r])
    func_def.body = ril_body

    # pass 1: early-return restructuring; pass 2: break/continue lowering.
    # Both are pure AST->AST and must run before the control-flow
    # transformer so it only ever sees jump-free loops and tail returns.
    _lower_returns(func_def)
    jl = _JumpLowering()
    lowered_body = []
    for s in func_def.body:
        r = jl.visit(s)
        lowered_body.extend(r if isinstance(r, list) else [r])
    func_def.body = lowered_body

    arg_names = {a.arg for a in (func_def.args.posonlyargs +
                                 func_def.args.args +
                                 func_def.args.kwonlyargs)}
    if func_def.args.vararg:
        arg_names.add(func_def.args.vararg.arg)
    if func_def.args.kwarg:
        arg_names.add(func_def.args.kwarg.arg)
    local_names = arg_names | _stores(func_def.body)

    transformer = _ControlFlowTransformer(local_names)
    func_def.body = [transformer.visit(s) for s in func_def.body]
    # flatten lists returned by statement replacements
    def _flatten(stmts):
        out = []
        for s in stmts:
            if isinstance(s, list):
                out.extend(_flatten(s))
            else:
                out.append(s)
        return out
    func_def.body = _flatten(func_def.body)
    ast.fix_missing_locations(tree)

    glb = dict(getattr(fn, "__globals__", {}))
    import sys
    glb[_JST] = sys.modules[__name__]
    if getattr(fn, "__closure__", None):
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                glb[name] = cell.cell_contents
            except ValueError:
                pass
    try:
        code = compile(tree, filename=f"<dy2static:{fn.__name__}>",
                       mode="exec")
        exec(code, glb)
        converted = glb[func_def.name]
    except Exception:
        return fn  # conversion must never break a function that traces fine
    converted = functools.wraps(fn)(converted)
    converted.__ptpu_dy2static__ = True
    _cache_put(fn, converted)
    return converted
