"""to_static implementation (analogue of python/paddle/jit/api.py:233)."""

from __future__ import annotations

import functools
import os
import pickle
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..core import dispatch as _dispatch
from ..core import generator as _generator
from ..core import tape as _tape
from ..core.tensor import Tensor


class InputSpec:
    """Analogue of paddle.static.InputSpec."""

    def __init__(self, shape, dtype="float32", name=None):
        from ..core.dtypes import convert_dtype
        self.shape = tuple(shape)
        self.dtype = convert_dtype(dtype)
        self.name = name


def _tree_key(args, kwargs, training):
    def leaf_key(x):
        if isinstance(x, Tensor):
            return ("T", tuple(x._value.shape), str(x._value.dtype))
        if isinstance(x, jax.Array):
            return ("A", tuple(x.shape), str(x.dtype))
        return ("L", repr(x))

    flat, treedef = jax.tree_util.tree_flatten(
        (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
    return (tuple(leaf_key(x) for x in flat), str(treedef), training)


class StaticFunction:
    """A traced+compiled callable with per-signature cache (the analogue of
    ProgramTranslator's ConcreteProgram cache)."""

    def __init__(self, function, input_spec=None, build_strategy=None,
                 backend=None, full_graph=True):
        functools.update_wrapper(self, function)
        if full_graph:
            # dy2static: rewrite Python if/while/for-range over tensor
            # predicates into lax control flow (see jit/dy2static.py; the
            # reference's AST transformer stack)
            from .dy2static import convert_to_static
            function = convert_to_static(function)
        self._function = function
        self._input_spec = input_spec
        self._cache = {}
        self._instance = None  # bound Layer when used as a method decorator

    def __get__(self, instance, owner):
        bound = StaticFunction(self._function, self._input_spec)
        bound._instance = instance
        bound._cache = self._cache
        return bound

    @property
    def function(self):
        return self._function

    def _call_eager(self, *args, **kwargs):
        if self._instance is not None:
            return self._function(self._instance, *args, **kwargs)
        return self._function(*args, **kwargs)

    def _build(self, key, args, kwargs, training):
        # ---- discovery pass: which Parameters does the function read? ----
        store = {}
        _dispatch.set_param_tracker(store)
        try:
            with _tape.no_grad():
                self._call_eager(*args, **kwargs)
        finally:
            _dispatch.set_param_tracker(None)
        params = list(store.values())

        flat_in, in_treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        tensor_slots = [i for i, x in enumerate(flat_in)
                        if isinstance(x, (Tensor, jax.Array))]
        const_leaves = [x for i, x in enumerate(flat_in)
                        if i not in tensor_slots]

        out_treedef_box = {}
        call_eager = self._call_eager

        def pure_fn(rng_key, *arrays):
            n_p = len(params)
            p_arrays = arrays[:n_p]
            in_arrays = arrays[n_p:]
            saved = [p._value for p in params]
            _generator.push_trace_key(rng_key)
            try:
                for p, a in zip(params, p_arrays):
                    p._value = a
                leaves = list(flat_in)
                for slot, arr in zip(tensor_slots, in_arrays):
                    leaves[slot] = Tensor(arr)
                a2, k2 = jax.tree_util.tree_unflatten(in_treedef, leaves)
                with _tape.no_grad():
                    if self._instance is not None:
                        out = self._function(self._instance, *a2, **k2)
                    else:
                        out = self._function(*a2, **k2)
            finally:
                for p, s in zip(params, saved):
                    p._value = s
                _generator.pop_trace_key()
            out_flat, out_treedef = jax.tree_util.tree_flatten(
                out, is_leaf=lambda x: isinstance(x, Tensor))
            out_treedef_box["treedef"] = out_treedef
            out_treedef_box["is_tensor"] = [isinstance(x, (Tensor, jax.Array))
                                            for x in out_flat]
            out_treedef_box["const"] = [None if isinstance(x, (Tensor, jax.Array))
                                        else x for x in out_flat]
            return tuple(x._value if isinstance(x, Tensor) else jnp.asarray(x)
                         for x in out_flat
                         if isinstance(x, (Tensor, jax.Array)))

        jitted = jax.jit(pure_fn)
        entry = {
            "jitted": jitted,
            "params": params,
            "tensor_slots": tensor_slots,
            "out_box": out_treedef_box,
        }
        self._cache[key] = entry
        return entry

    def __call__(self, *args, **kwargs):
        training = bool(getattr(self._instance, "training", False))
        key = _tree_key(args, kwargs, training)
        entry = self._cache.get(key)
        if entry is None:
            entry = self._build(key, args, kwargs, training)
        params = entry["params"]
        flat_in, _ = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        input_tensors = [flat_in[i] for i in entry["tensor_slots"]]
        rng_key = _generator.default_generator().next_key()

        def jit_impl(*arrays, _jitted=entry["jitted"], _key=rng_key):
            return _jitted(_key, *arrays)

        outs = _dispatch.dispatch(
            "jit_program", jit_impl, tuple(params) + tuple(input_tensors))
        outs = outs if isinstance(outs, tuple) else (outs,)
        box = entry["out_box"]
        out_flat = []
        it = iter(outs)
        for is_t, const in zip(box["is_tensor"], box["const"]):
            out_flat.append(next(it) if is_t else const)
        return jax.tree_util.tree_unflatten(box["treedef"], out_flat)

    def concrete_program_specify_input_spec(self, *a, **k):
        return None


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """Mirror paddle.jit.to_static (decorator or call form)."""

    def decorate(fn):
        from ..nn.layer.layers import Layer
        if isinstance(fn, Layer):
            layer = fn
            static = StaticFunction(type(layer).forward, input_spec)
            static._instance = layer
            layer.forward = static
            return layer
        return StaticFunction(fn, input_spec)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn=None):
    if fn is None:
        return lambda f: f
    return fn


def ignore_module(modules):
    pass


class TranslatedLayer:
    """Loaded inference program (analogue of jit/translated_layer.py):
    wraps a deserialized StableHLO executable + weights."""

    def __init__(self, exported, state, in_spec):
        self._exported = exported
        self._state = state
        self._in_spec = in_spec
        self.training = False

    def __call__(self, *args):
        arrays = [a._value if isinstance(a, Tensor) else jnp.asarray(a)
                  for a in args]
        out = self._exported.call(*self._state, *arrays)
        if isinstance(out, (tuple, list)):
            return tuple(Tensor(o) for o in out)
        return Tensor(out)

    def eval(self):
        return self

    def state_dict(self):
        return {str(i): Tensor(a) for i, a in enumerate(self._state)}


def save(layer, path, input_spec=None, precision=None, **configs):
    """Serialize a compiled inference program + weights.

    TPU-native analogue of paddle.jit.save (reference python/paddle/jit/api.py
    save): the forward is exported to portable StableHLO via jax.export, the
    weights to a pickle — loadable without the model's Python class.

    ``precision``: export-time compute dtype ("bfloat16"/"float16") — float
    params are cast and float inputs converted at the program boundary, so
    the exported StableHLO computes natively in the low precision.  This is
    where the reference's inference precision conversion happens
    (convert_to_mixed_precision / analysis_config precision modes); on TPU
    precision is a property of the traced program, chosen at export.
    """
    from ..nn.layer.layers import Layer
    if input_spec is None:
        raise ValueError("jit.save requires input_spec on TPU (static shapes)")
    if precision is not None:
        from ..core.dtypes import convert_dtype
        precision = str(convert_dtype(precision))
        if precision not in ("bfloat16", "float16"):
            raise ValueError(
                f"jit.save precision must be bfloat16/float16, got "
                f"{precision!r}")
    specs = [s if isinstance(s, InputSpec) else InputSpec(s.shape, s.dtype)
             for s in input_spec]
    from .dy2static import convert_to_static
    if isinstance(layer, Layer):
        layer.eval()
        params = [(k, v) for k, v in layer.state_dict().items()]
        fn = layer.forward
        if isinstance(fn, StaticFunction):
            fn = functools.partial(fn._function, layer)
        else:
            # convert Python control flow in forward like the reference
            # jit.save does (its to_static program translation)
            raw = getattr(fn, "__func__", None)
            if raw is not None:
                conv = convert_to_static(raw)
                if conv is not raw:
                    fn = functools.partial(conv, layer)
    else:
        params = []
        fn = layer
        if callable(fn):
            bound_self = getattr(fn, "__self__", None)
            raw = getattr(fn, "__func__", None)
            if bound_self is not None and raw is not None:
                # bound method: convert the underlying function and rebind
                # self, else traced inputs would shift into the self slot
                conv = convert_to_static(raw)
                if conv is not raw:
                    fn = functools.partial(conv, bound_self)
            else:
                fn = convert_to_static(fn)

    names = [k for k, _ in params]
    values = [v._value for _, v in params]
    if precision is not None:
        pdt = jnp.dtype(precision)
        values = [v.astype(pdt) if jnp.issubdtype(v.dtype, jnp.floating)
                  else v for v in values]

    def pure(p_values, *inputs):
        if precision is not None:
            pdt_ = jnp.dtype(precision)
            inputs = tuple(
                i.astype(pdt_) if jnp.issubdtype(
                    jnp.asarray(i).dtype, jnp.floating) else i
                for i in inputs)
        from ..nn.layer.layers import Layer as _L
        if isinstance(layer, _L):
            saved = {}
            sd = layer.state_dict()
            for (k, t), new in zip(sd.items(), p_values):
                saved[k] = t._value
                t._value = new
            try:
                with _tape.no_grad():
                    out = fn(*[Tensor(i) for i in inputs])
            finally:
                for k, t in sd.items():
                    t._value = saved[k]
        else:
            with _tape.no_grad():
                out = fn(*[Tensor(i) for i in inputs])
        flat, _ = jax.tree_util.tree_flatten(
            out, is_leaf=lambda x: isinstance(x, Tensor))
        return tuple(x._value if isinstance(x, Tensor) else x for x in flat)

    from jax import export as jax_export

    # None / -1 dims (dynamic batch) become export-time symbolic dims
    scope = None
    in_shapes = []
    for si, s in enumerate(specs):
        if any(d is None or d == -1 for d in s.shape):
            if scope is None:
                scope = jax_export.SymbolicScope()
            dimstr = ",".join(
                f"dyn{si}_{j}" if (d is None or d == -1) else str(d)
                for j, d in enumerate(s.shape))
            shape = jax_export.symbolic_shape(dimstr, scope=scope)
        else:
            shape = s.shape
        in_shapes.append(jax.ShapeDtypeStruct(shape, s.dtype))
    p_shapes = [jax.ShapeDtypeStruct(v.shape, v.dtype) for v in values]

    jitted = jax.jit(lambda pv, *i: pure(pv, *i))
    try:  # portable artifact: loadable on either host CPU or TPU
        exp = jax_export.export(jitted, platforms=("cpu", "tpu"))(
            p_shapes, *in_shapes)
    except Exception:
        exp = jax_export.export(jitted)(p_shapes, *in_shapes)
    blob = exp.serialize()

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path + ".ptpu_model", "wb") as f:
        f.write(blob)
    import numpy as np
    with open(path + ".ptpu_params", "wb") as f:
        pickle.dump({"names": names,
                     "values": [np.asarray(v) for v in values],
                     "in_spec": [(s.shape, str(s.dtype)) for s in specs],
                     "precision": precision}, f)


def load(path, **configs):
    from jax import export as jax_export
    with open(path + ".ptpu_model", "rb") as f:
        exp = jax_export.deserialize(f.read())
    with open(path + ".ptpu_params", "rb") as f:
        meta = pickle.load(f)
    values = [jnp.asarray(v) for v in meta["values"]]

    class _Loaded(TranslatedLayer):
        def __call__(self, *args):
            arrays = [a._value if isinstance(a, Tensor) else jnp.asarray(a)
                      for a in args]
            out = self._exported.call(values, *arrays)
            if isinstance(out, (tuple, list)):
                outs = tuple(Tensor(o) for o in out)
                return outs if len(outs) > 1 else outs[0]
            return Tensor(out)

    return _Loaded(exp, values, meta["in_spec"])
