"""TrainStep — a fully-compiled training step.

The flagship perf path: forward + loss + backward + optimizer update traced
and compiled as ONE XLA program with donated buffers (params and optimizer
state update in place in HBM).  This is the TPU-native equivalent of the
reference's static-graph training executor (SURVEY §3.2): one fused program,
zero python per-op overhead, and — under a device mesh — GSPMD shards it
across DP/TP/PP axes from the layer/param sharding annotations.

Supported optimizers: SGD / Momentum / Adam / AdamW (the training recipes in
BASELINE.md).  Other optimizers fall back to `step_eager`.
"""

from __future__ import annotations

import functools
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..core import generator as _generator
from ..core import tape as _tape
from ..core.tensor import Tensor
from ..observability import metrics as _obs
from ..observability.spans import span as _span
from ..optimizer import SGD, Adam, AdamW, Momentum
from ..optimizer.optimizer import Optimizer


_UNSET = object()


class _TrainStepInstruments:
    """Registry handles for the train-step hot path (shared across
    TrainStep instances; created once on first use).  A "compile" is
    the first dispatch of a (TrainStep, block size) pair — jax traces
    and XLA-compiles inside that call, so its wall time IS the compile
    duration (plus one step of execution, which is noise next to
    multi-second XLA compiles at real scale)."""

    _inst = None

    def __init__(self):
        r = _obs.get_registry()
        self.compiles = r.counter(
            "train_step.compiles", "XLA (re)compilations of the fused "
            "train step (first dispatch per executable)")
        self.compile_seconds = r.histogram(
            "train_step.compile_seconds",
            "trace + compile + first-step wall time",
            buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                     120.0, 300.0))
        self.cache_hits = r.counter(
            "train_step.cache_hits", "dispatches served by an existing "
            "compiled executable")
        self.cache_misses = r.counter(
            "train_step.cache_misses", "dispatches that had to build an "
            "executable")
        self.step_seconds = r.histogram(
            "train_step.step_seconds", "per-call wall time of the "
            "compiled step (async dispatch; excludes compile calls)")

    @classmethod
    def get(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst

    def record_dispatch(self, was_compile: bool, dt: float):
        """Account one dispatch: compile calls land in the compile
        histogram, steady-state calls in the step histogram."""
        if was_compile:
            self.compiles.inc()
            self.cache_misses.inc()
            self.compile_seconds.observe(dt)
        else:
            self.cache_hits.inc()
            self.step_seconds.observe(dt)


def _functional_sgd(p, g, state, lr, hp):
    # fp32 lr must not promote a bf16 param: cast the delta, not the result
    return p - (lr * g).astype(p.dtype), state


def _functional_momentum(p, g, state, lr, hp):
    v = state["velocity"]
    g = g.astype(v.dtype)
    v_new = hp["momentum"] * v + g
    if hp["nesterov"]:
        p_new = p - (lr * (g + hp["momentum"] * v_new)).astype(p.dtype)
    else:
        p_new = p - (lr * v_new).astype(p.dtype)
    return p_new, {"velocity": v_new}


def _stochastic_round_bf16(x, key):
    """Unbiased f32 -> bf16: add uniform 16-bit noise below the bf16
    mantissa boundary, then truncate (E[result] == x; plain
    round-to-nearest would bias an EMA that accumulates thousands of
    sub-ULP updates).

    Noise economics at 1.1B-param scale: threefry (jax.random.randint)
    costs ~40 ms/step of generation, and a full-size rng_bit_generator
    buffer is a 4.4 GB HBM transient (measured OOM).  Instead ONE small
    hardware-RBG tile per store is broadcast across leading dims.

    Within-step COLUMN CORRELATION (a property, not a bug): because the
    noise tile has only the trailing shape, every element sharing a
    trailing index (same "column", different leading rows) adds the
    SAME 16-bit noise value in a given step — their rounding errors are
    perfectly correlated within that step.  This sits next to the
    EMA-unbiasedness argument deliberately: unbiasedness needs
    per-element noise that is uniform and independent across STEPS
    (the fresh per-step key provides that), so E[m_t] per element is
    exact regardless of within-step correlation.  What the correlation
    DOES structure is same-step cross-element error: any consumer of a
    same-step spatial statistic over the stored moments (e.g. the
    variance of a column mean) sees column-correlated rounding noise,
    not i.i.d. noise.  The optimizer never computes such a statistic.

    SHAPE-PRESERVING (round 5): the round-4 form flattened x to
    [-1, 64Ki] around the noise add — on TPU that reshape physically
    relayouts the tiled array TWICE per moment store, which at 1.1B
    params was most of the optimizer sweep's 70-109 ms.  The noise tile
    is now one trailing-shape row broadcast across leading dims — pure
    elementwise traffic."""
    kd = jax.random.key_data(key).astype(jnp.uint32).reshape(-1)
    seed = jnp.tile(kd, 2)[:4] if kd.size < 4 else kd[:4]
    x1 = x.reshape(1) if x.ndim == 0 else x
    bits = jax.lax.bitcast_convert_type(x1, jnp.uint32)  # x's own shape
    # one trailing row of noise, broadcast (for free, inside the update
    # fusion) across every leading dim
    _, tile = jax.lax.rng_bit_generator(seed, x1.shape[-1:],
                                        dtype=jnp.uint32)
    noise = (bits + (tile & jnp.uint32(0xFFFF))) & jnp.uint32(0xFFFF0000)
    return jax.lax.bitcast_convert_type(noise, jnp.float32) \
        .astype(jnp.bfloat16).reshape(x.shape)


def _store_moment(val_f32, like, key):
    if like.dtype == jnp.float32:
        return val_f32
    if like.dtype == jnp.bfloat16 and key is not None:
        return _stochastic_round_bf16(val_f32, key)
    return val_f32.astype(like.dtype)


def _functional_adam(p, g, state, lr, hp, key=None):
    gf = g.astype(jnp.float32)
    pf = p.astype(jnp.float32)
    b1, b2, eps, wd = hp["beta1"], hp["beta2"], hp["epsilon"], hp["wd"]
    if hp["decoupled"]:
        pf = pf * (1.0 - lr * wd)
    elif wd:
        gf = gf + wd * pf
    t = state["t"] + 1
    m = b1 * state["m"].astype(jnp.float32) + (1 - b1) * gf
    v = b2 * state["v"].astype(jnp.float32) + (1 - b2) * gf * gf
    m_hat = m / (1 - b1 ** t)
    v_hat = v / (1 - b2 ** t)
    from ..core.flags import flag
    if flag("adamw_rsqrt_update"):
        # Adam's epsilon-hat variant (Kingma & Ba, footnote to Alg. 1):
        # eps INSIDE the sqrt — update = m_hat * rsqrt(v_hat + eps^2).
        # Equivalent scale at v=0 and v>>eps^2 (differs by <= sqrt(2)
        # between); v5e's VPU divide+sqrt chain stalls the update sweep,
        # and hardware rsqrt measured 25% faster at 60M params
        p_new = (pf - lr * m_hat * jax.lax.rsqrt(v_hat + eps * eps)) \
            .astype(p.dtype)
    else:
        p_new = (pf - lr * m_hat / (jnp.sqrt(v_hat) + eps)).astype(p.dtype)
    if key is not None:
        km, kv2 = jax.random.split(key)
    else:
        km = kv2 = None
    return p_new, {"m": _store_moment(m, state["m"], km),
                   "v": _store_moment(v, state["v"], kv2), "t": t}


def _fused_adam_ok(update_fn, hypers, mesh):
    """Route the update sweep through the Pallas fused AdamW kernel:
    XLA's per-param update fusions measured ~170-230 GB/s effective on
    v5e while the native-shape fused kernel streams near the HBM
    roofline — the sweep is pure HBM traffic, so this nearly halves it.
    Round 4's flat-view kernel relayouted every tiled param (~520 MB of
    copies at 60M params, 89 GB/s effective — worse than XLA); the
    round-5 kernel grids over the param's OWN 2-D layout, so only
    natively tileable params route here (``native_tileable``).
    Single-chip only (a sharded param would need the kernel under
    shard_map) and decoupled-wd AdamW only (Adam folds wd into the
    grad, which the kernel does not model).  bf16 moments store via the
    hardware-PRNG stochastic rounding inside the kernel."""
    from ..core.flags import flag
    from ..ops.pallas._common import on_tpu
    # adamw_rsqrt_update changes the epsilon semantics of the XLA path;
    # the kernel implements only the reference sqrt form — mixing both
    # within one model would silently apply two different updates
    return (update_fn is _functional_adam and hypers.get("decoupled")
            and mesh is None and on_tpu()
            and not flag("adamw_rsqrt_update")
            and bool(flag("use_fused_adamw_kernel")))


def _fused_adam_eligible(p, s):
    """Per-param gate: native 2-D tileable shape, float param, moments in
    fp32 or bf16 (the kernel's SR path)."""
    from ..ops.pallas.fused_optimizer import native_tileable
    if not jnp.issubdtype(p.dtype, jnp.floating):
        return False
    if not isinstance(s, dict) or s.get("m") is None:
        return False
    if s["m"].dtype not in (jnp.float32, jnp.bfloat16):
        return False
    return native_tileable(p.shape, p.dtype, s["m"].dtype)


def _fused_adam_update(p, g, state, lr, hp, key=None):
    from ..ops.pallas.fused_optimizer import fused_adamw_update
    t = state["t"] + 1
    seed = None
    if key is not None and state["m"].dtype == jnp.bfloat16:
        # i32 scalar seed for the kernel's hardware PRNG (fresh per step
        # via the step rng key; per-block offsets come from program ids)
        seed = jax.lax.bitcast_convert_type(
            jax.random.key_data(key).reshape(-1)[-1].astype(jnp.uint32),
            jnp.int32)
    p_new, m_new, v_new = fused_adamw_update(
        p, g, state["m"], state["v"], lr, t, beta1=hp["beta1"],
        beta2=hp["beta2"], epsilon=hp["epsilon"], weight_decay=hp["wd"],
        seed=seed)
    return p_new, {"m": m_new, "v": v_new, "t": t}


class TrainStep:
    def __init__(self, model, loss_fn: Callable, optimizer: Optimizer,
                 mesh=None, in_shardings=None, donate: bool = True,
                 accumulate_steps: int = 1, accumulate_avg: bool = True):
        """``accumulate_steps=k`` enables in-graph gradient merge
        (reference fleet gradient_merge meta-optimizer): every call
        accumulates grads into fp32 buffers; the optimizer applies them
        on each k-th call under ``lax.cond`` (averaged when
        ``accumulate_avg``) — zero host-side branching."""
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh
        self._params = [p for p in model.parameters() if not p.stop_gradient]
        self._buffers = list(model.buffers())
        self._state = None
        self._compiled = None
        self._batch_sharding_cache = _UNSET
        self._update_fn, self._hypers = self._select_update(optimizer)
        if accumulate_steps < 1:
            raise ValueError(
                f"accumulate_steps must be >= 1, got {accumulate_steps}")
        self._accum_steps = accumulate_steps
        self._accum_avg = accumulate_avg
        self._gm_state = None

    def _select_update(self, opt):
        # multi_precision=False follows the reference contract: moments
        # live in the PARAM dtype (paddle adamw kernel's mp_ branch is
        # the fp32 path).  bf16 moments store via stochastic rounding —
        # plain round-to-nearest would bias the EMAs; with SR the
        # optimizer-state HBM sweep halves (BASELINE.md round 4).  The
        # noise tile is shared across leading dims, so same-step
        # rounding errors are COLUMN-correlated — unbiasedness per
        # element survives, same-step spatial statistics would not; see
        # the trade-off note in _stochastic_round_bf16's docstring
        if isinstance(opt, AdamW):
            return _functional_adam, {
                "beta1": opt._beta1, "beta2": opt._beta2,
                "epsilon": opt._epsilon, "wd": opt._weight_decay,
                "decoupled": True,
                "multi_precision": bool(getattr(opt, "_multi_precision",
                                                True))}
        if isinstance(opt, Adam):
            return _functional_adam, {
                "beta1": opt._beta1, "beta2": opt._beta2,
                "epsilon": opt._epsilon, "wd": opt._weight_decay,
                "decoupled": False,
                "multi_precision": bool(getattr(opt, "_multi_precision",
                                                True))}
        if isinstance(opt, Momentum):
            return _functional_momentum, {
                "momentum": opt._momentum, "nesterov": opt._use_nesterov}
        if isinstance(opt, SGD):
            return _functional_sgd, {}
        return None, None

    def _compile_probe(self, fn, flag_attr: str):
        """Closure that, called AFTER a dispatch of ``fn``, reports
        whether that dispatch traced+compiled: jit-cache growth when
        jax's private ``_cache_size`` probe exists (catches shape-change
        retraces too), else a first-dispatch flag on ``self``."""
        csize = getattr(fn, "_cache_size", None)
        if csize is not None:
            try:
                n0 = csize()
                return lambda: csize() > n0
            except Exception:
                pass
        first = not getattr(self, flag_attr, False)

        def probe():
            # flag set only here, AFTER a successful dispatch: if the
            # first dispatch raised, the retry still counts as compile
            setattr(self, flag_attr, True)
            return first

        return probe

    def _mesh(self):
        """Resolve mesh= (accepts jax Mesh, ProcessMesh, or None→global)."""
        if self.mesh is None:
            from ..distributed.topology import get_global_mesh
            return get_global_mesh()
        from ..distributed.sharding_api import _resolve_mesh
        return _resolve_mesh(self.mesh)

    def _opt_state_spec(self, p, mesh):
        """PartitionSpec for a param's optimizer state: inherit the param's
        sharding; under ZeRO (shard_optimizer) additionally shard the first
        free divisible dim over the 'sharding' axis (ZeRO-1 layout)."""
        from jax.sharding import PartitionSpec
        spec = list(p._dist_attr) if p._dist_attr is not None \
            else [None] * p._value.ndim
        while len(spec) < p._value.ndim:
            spec.append(None)

        def uses_axis(entry, name):
            return entry == name or (isinstance(entry, tuple) and name in entry)

        if getattr(self.optimizer, "_zero_sharded", False) and \
                "sharding" in mesh.axis_names and mesh.shape["sharding"] > 1 \
                and not any(uses_axis(e, "sharding") for e in spec):
            from ..distributed.sharding_api import shard_first_divisible_dim
            shard_first_divisible_dim(spec, p._value.shape,
                                      mesh.shape["sharding"])
        return PartitionSpec(*spec)

    def _opt_state_sharding(self, p):
        from jax.sharding import NamedSharding
        mesh = self._mesh()
        if mesh is None:
            return None
        return NamedSharding(mesh, self._opt_state_spec(p, mesh))

    def _place(self, arr, sharding):
        if sharding is None:
            return arr
        return jax.device_put(arr, sharding)

    def _init_state(self):
        def zeros_like_placed(p, dtype=None):
            arr = jnp.zeros(p._value.shape, dtype or p._value.dtype)
            return self._place(arr, self._opt_state_sharding(p))

        if self._update_fn is _functional_adam:
            # moment dtype: fp32 under multi_precision (default); with
            # multi_precision=False, bf16 params get bf16 moments (the
            # reference contract, stored via stochastic rounding).  fp16
            # params STAY fp32: fp16's 5-bit exponent overflows v at
            # |grad| > ~256, and the SR path is bf16-only
            def mdt(p):
                if self._hypers.get("multi_precision", True):
                    return jnp.float32
                return (jnp.bfloat16 if p._value.dtype == jnp.bfloat16
                        else jnp.float32)
            return [{"m": zeros_like_placed(p, mdt(p)),
                     "v": zeros_like_placed(p, mdt(p)),
                     "t": jnp.zeros((), jnp.float32)} for p in self._params]
        if self._update_fn is _functional_momentum:
            return [{"velocity": zeros_like_placed(p)}
                    for p in self._params]
        return [{} for _ in self._params]

    def _build(self):
        params = self._params
        update_fn = self._update_fn
        hypers = self._hypers
        model = self.model
        loss_fn = self.loss_fn
        grad_clip = self.optimizer._grad_clip

        # Output-sharding pins: keep updated params/state on their input
        # layouts so ZeRO sharding survives step 1 and donation holds.
        mesh = self._mesh()
        fused_adam = _fused_adam_ok(update_fn, hypers, mesh)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            # unannotated params pin REPLICATED: ZeRO stage-1/2 updates run
            # on opt-state shards, and this pin is the stage-1 post-update
            # all-gather — without it XLA would leave the new params
            # sharded (silently promoting the layout to stage-3)
            param_pins = [
                NamedSharding(mesh, PartitionSpec(*p._dist_attr))
                if p._dist_attr is not None
                else NamedSharding(mesh, PartitionSpec())
                for p in params
            ]
            state_pins = [NamedSharding(mesh, self._opt_state_spec(p, mesh))
                          for p in params]
        else:
            param_pins = [None] * len(params)
            state_pins = [None] * len(params)

        # ZeRO stage-2/3: gradients take the opt-state sharding (see the
        # constraint below at the value_and_grad site)
        grad_pins = None
        if mesh is not None and getattr(
                self.optimizer, "_group_sharded_level", None) in (
                    "os_g", "p_g_os"):
            grad_pins = [
                pin if pin is not None and any(
                    e is not None for e in self._opt_state_spec(p, mesh))
                else None
                for p, pin in zip(params, state_pins)]

        def pin(arr, sharding, like_shape):
            if sharding is None or arr.shape != like_shape:
                return arr
            return jax.lax.with_sharding_constraint(arr, sharding)

        buffers = self._buffers

        accum_steps = self._accum_steps
        accum_avg = self._accum_avg

        def compiled(p_values, opt_state, gm_state, rng_key, lr, b_values,
                     *inputs):
            def loss_of(pv):
                saved = [p._value for p in params]
                saved_b = [b._value for b in buffers]
                _generator.push_trace_key(rng_key)
                try:
                    for p, a in zip(params, pv):
                        p._value = a
                    for b, a in zip(buffers, b_values):
                        b._value = a
                    with _tape.no_grad():
                        out = loss_fn(model, *[Tensor(i) for i in inputs])
                    # mutable buffers (e.g. BatchNorm running stats) updated
                    # in-place during the traced forward come out as aux so
                    # no tracer leaks into module state
                    new_b = [b._value for b in buffers]
                finally:
                    for p, s in zip(params, saved):
                        p._value = s
                    for b, s in zip(buffers, saved_b):
                        b._value = s
                    _generator.pop_trace_key()
                loss_t = out[0] if isinstance(out, tuple) else out
                aux = out[1:] if isinstance(out, tuple) else ()
                return loss_t._value, (tuple(
                    a._value if isinstance(a, Tensor) else a
                    for a in aux), new_b)

            (loss, (aux, new_b)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(list(p_values))
            if grad_pins is not None:
                # ZeRO stage-2/3 (os_g / p_g_os): pin each gradient to its
                # optimizer-state sharding so XLA reduce-scatters the grad
                # once and the whole update runs on 1/N shards — gradients
                # never materialize replicated (reference
                # group_sharded_stage2 reduce-scatter hooks)
                grads = [g if gpin is None else
                         jax.lax.with_sharding_constraint(g, gpin)
                         for g, gpin in zip(grads, grad_pins)]
            def apply_update(p_vals, grads_in, opt_in):
                gs = list(grads_in)
                if grad_clip is not None and hasattr(grad_clip, "clip_norm"):
                    gsq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                              for g in gs)
                    gnorm = jnp.sqrt(gsq)
                    cn = grad_clip.clip_norm
                    scale = cn / jnp.maximum(gnorm, cn)
                    gs = [g * scale.astype(g.dtype) for g in gs]
                new_p, new_s = [], []
                for i, (p, g, s) in enumerate(zip(p_vals, gs, opt_in)):
                    fn_i = (_fused_adam_update
                            if fused_adam and _fused_adam_eligible(p, s)
                            else update_fn)
                    if fn_i in (_functional_adam, _fused_adam_update) \
                            and isinstance(s, dict) \
                            and s.get("m") is not None \
                            and s["m"].dtype == jnp.bfloat16:
                        # bf16 moments store via stochastic rounding —
                        # a per-param key far from the dropout stream
                        np_, ns_ = fn_i(p, g, s, lr, hypers,
                                        key=jax.random.fold_in(
                                            rng_key, 1 << 20 | i))
                    else:
                        np_, ns_ = fn_i(p, g, s, lr, hypers)
                    np_ = pin(np_, param_pins[i], p.shape)
                    ns_ = {k: pin(v, state_pins[i], p.shape)
                           for k, v in ns_.items()}
                    new_p.append(np_)
                    new_s.append(ns_)
                return new_p, new_s

            if accum_steps == 1:
                new_p, new_s = apply_update(p_values, grads, opt_state)
                return new_p, new_s, gm_state, loss, aux, new_b

            # gradient merge: accumulate into fp32 buffers; the optimizer
            # fires on every accum_steps-th call under lax.cond (reference
            # gradient_merge_optimizer's conditional block)
            acc = [a + g.astype(jnp.float32)
                   for a, g in zip(gm_state["acc"], grads)]
            count = gm_state["count"] + 1
            fire = (count % accum_steps) == 0

            def fire_branch(operands):
                p_vals, opt_in, acc_in = operands
                gscale = (1.0 / accum_steps) if accum_avg else 1.0
                gs = [(a * gscale).astype(p.dtype)
                      for a, p in zip(acc_in, p_vals)]
                new_p, new_s = apply_update(p_vals, gs, opt_in)
                return (new_p, new_s, [jnp.zeros_like(a) for a in acc_in])

            def hold_branch(operands):
                p_vals, opt_in, acc_in = operands
                return (list(p_vals), list(opt_in), list(acc_in))

            new_p, new_s, new_acc = jax.lax.cond(
                fire, fire_branch, hold_branch,
                (list(p_values), list(opt_state), acc))
            return (new_p, new_s, {"acc": new_acc, "count": count},
                    loss, aux, new_b)

        jit_kwargs = dict(donate_argnums=(0, 1, 2))
        self._step_fn = compiled
        self._compiled = jax.jit(compiled, **jit_kwargs)

    def _batch_sharding(self):
        """NamedSharding for batch inputs: dim 0 over the 'data'
        (+'sharding' fused ZeRO-DP) axes, replicated elsewhere.  Depends
        only on the mesh — computed once and cached."""
        if self._batch_sharding_cache is not _UNSET:
            return self._batch_sharding_cache
        from jax.sharding import NamedSharding, PartitionSpec
        mesh = self._mesh()
        sharding = None
        n_shards = 1
        if mesh is not None:
            batch_axes = [a for a in ("data", "sharding")
                          if a in mesh.axis_names and mesh.shape[a] > 1]
            if batch_axes:
                for a in batch_axes:
                    n_shards *= mesh.shape[a]
                spec = PartitionSpec(tuple(batch_axes) if len(batch_axes) > 1
                                     else batch_axes[0])
                sharding = NamedSharding(mesh, spec)
        self._batch_sharding_cache = (sharding, n_shards)
        return self._batch_sharding_cache

    def _shard_batch(self, x):
        """Place a batch input over the data axes.  Inputs carrying an
        explicit user sharding annotation (Tensor._dist_attr) are respected
        and left untouched."""
        if isinstance(x, Tensor) and x._dist_attr is not None:
            return x._value
        arr = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        sharding, n_shards = self._batch_sharding()
        if sharding is None or arr.ndim == 0 or arr.shape[0] % n_shards != 0:
            return arr
        if getattr(arr, "sharding", None) == sharding:
            return arr
        return jax.device_put(arr, sharding)

    def _init_gm_state(self):
        if self._accum_steps == 1:
            return ()
        return {"acc": [self._place(jnp.zeros(p._value.shape, jnp.float32),
                                    self._opt_state_sharding(p))
                        for p in self._params],
                "count": jnp.zeros((), jnp.int32)}

    def run_steps(self, *inputs, steps: int):
        """Run ``steps`` consecutive train steps on the SAME batch inside
        ONE compiled call (``lax.scan`` over the step body, fresh RNG key
        per iteration, constant lr).  Amortizes per-dispatch host latency —
        benchmarking/microbenchmark use; real epochs feed fresh batches
        through ``__call__``.  Returns the last step's loss."""
        m = _TrainStepInstruments.get()
        if self._state is None:
            self._state = self._init_state()
            self._gm_state = self._init_gm_state()
            with _span("train_step.build"):
                self._build()
        if not hasattr(self, "_multi_cache"):
            self._multi_cache = {}
        fn = self._multi_cache.get(steps)
        if fn is None:
            step_fn = self._step_fn

            def multi(p_values, opt_state, gm_state, key, lr, b_values,
                      *inp):
                def body(carry, i):
                    p, s, gm, b, k = carry
                    k = jax.random.fold_in(k, i)
                    new_p, new_s, new_gm, loss, _aux, new_b = step_fn(
                        p, s, gm, k, lr, b, *inp)
                    return (list(new_p), list(new_s), new_gm,
                            list(new_b), k), loss

                carry0 = (list(p_values), list(opt_state), gm_state,
                          list(b_values), key)
                (p, s, gm, b, _k), losses = jax.lax.scan(
                    body, carry0, jnp.arange(steps))
                return p, s, gm, losses[-1], b

            fn = jax.jit(multi, donate_argnums=(0, 1, 2))
            self._multi_cache[steps] = fn
        arrays = [self._shard_batch(i) for i in inputs]
        key = _generator.default_generator().next_key()
        lr = jnp.float32(self.optimizer.get_lr())
        p_values = [p._value for p in self._params]
        b_values = [b._value for b in self._buffers]
        probe = self._compile_probe(fn, f"_dispatched_multi_{steps}")
        t0 = time.perf_counter()
        with _span("train_step.run_steps", steps=steps):
            new_p, self._state, self._gm_state, loss, new_b = fn(
                p_values, self._state, self._gm_state, key, lr, b_values,
                *arrays)
        m.record_dispatch(probe(), time.perf_counter() - t0)
        for p, v in zip(self._params, new_p):
            p._value = v
        for b, v in zip(self._buffers, new_b):
            b._value = v
        return Tensor(loss)

    def __call__(self, *inputs):
        m = _TrainStepInstruments.get()
        if self._state is None:
            self._state = self._init_state()
            self._gm_state = self._init_gm_state()
            with _span("train_step.build"):
                self._build()
        # a dispatch that grows the jit executable cache is a compile —
        # catches the first call AND input-shape-change retraces (which
        # would otherwise pollute the step-time histogram with
        # multi-second outliers); falls back to a first-dispatch flag
        # where the private _cache_size probe is unavailable
        probe = self._compile_probe(self._compiled, "_dispatched")
        arrays = [self._shard_batch(i) for i in inputs]
        key = _generator.default_generator().next_key()
        lr = jnp.float32(self.optimizer.get_lr())
        p_values = [p._value for p in self._params]
        b_values = [b._value for b in self._buffers]
        t0 = time.perf_counter()
        with _span("train_step.call"):
            new_p, self._state, self._gm_state, loss, aux, new_b = \
                self._compiled(
                    p_values, self._state, self._gm_state, key, lr,
                    b_values, *arrays)
        m.record_dispatch(probe(), time.perf_counter() - t0)
        for p, v in zip(self._params, new_p):
            p._value = v
        for b, v in zip(self._buffers, new_b):
            b._value = v
        loss_t = Tensor(loss)
        if aux:
            return (loss_t,) + tuple(Tensor(a) for a in aux)
        return loss_t
