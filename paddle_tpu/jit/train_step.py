"""TrainStep — a fully-compiled training step.

The flagship perf path: forward + loss + backward + optimizer update traced
and compiled as ONE XLA program with donated buffers (params and optimizer
state update in place in HBM).  This is the TPU-native equivalent of the
reference's static-graph training executor (SURVEY §3.2): one fused program,
zero python per-op overhead, and — under a device mesh — GSPMD shards it
across DP/TP/PP axes from the layer/param sharding annotations.

Supported optimizers: SGD / Momentum / Adam / AdamW (the training recipes in
BASELINE.md).  Other optimizers fall back to `step_eager`.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..core import generator as _generator
from ..core import tape as _tape
from ..core.tensor import Tensor
from ..optimizer import SGD, Adam, AdamW, Momentum
from ..optimizer.optimizer import Optimizer


def _functional_sgd(p, g, state, lr, hp):
    return p - lr * g.astype(p.dtype), state


def _functional_momentum(p, g, state, lr, hp):
    v = state["velocity"]
    g = g.astype(p.dtype)
    v_new = hp["momentum"] * v + g
    if hp["nesterov"]:
        p_new = p - lr * (g + hp["momentum"] * v_new)
    else:
        p_new = p - lr * v_new
    return p_new, {"velocity": v_new}


def _functional_adam(p, g, state, lr, hp):
    gf = g.astype(jnp.float32)
    pf = p.astype(jnp.float32)
    b1, b2, eps, wd = hp["beta1"], hp["beta2"], hp["epsilon"], hp["wd"]
    if hp["decoupled"]:
        pf = pf * (1.0 - lr * wd)
    elif wd:
        gf = gf + wd * pf
    t = state["t"] + 1
    m = b1 * state["m"] + (1 - b1) * gf
    v = b2 * state["v"] + (1 - b2) * gf * gf
    m_hat = m / (1 - b1 ** t)
    v_hat = v / (1 - b2 ** t)
    p_new = (pf - lr * m_hat / (jnp.sqrt(v_hat) + eps)).astype(p.dtype)
    return p_new, {"m": m, "v": v, "t": t}


class TrainStep:
    def __init__(self, model, loss_fn: Callable, optimizer: Optimizer,
                 mesh=None, in_shardings=None, donate: bool = True):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh
        self._params = [p for p in model.parameters() if not p.stop_gradient]
        self._buffers = list(model.buffers())
        self._state = None
        self._compiled = None
        self._update_fn, self._hypers = self._select_update(optimizer)

    def _select_update(self, opt):
        if isinstance(opt, AdamW):
            return _functional_adam, {
                "beta1": opt._beta1, "beta2": opt._beta2,
                "epsilon": opt._epsilon, "wd": opt._weight_decay,
                "decoupled": True}
        if isinstance(opt, Adam):
            return _functional_adam, {
                "beta1": opt._beta1, "beta2": opt._beta2,
                "epsilon": opt._epsilon, "wd": opt._weight_decay,
                "decoupled": False}
        if isinstance(opt, Momentum):
            return _functional_momentum, {
                "momentum": opt._momentum, "nesterov": opt._use_nesterov}
        if isinstance(opt, SGD):
            return _functional_sgd, {}
        return None, None

    def _init_state(self):
        if self._update_fn is _functional_adam:
            return [{"m": jnp.zeros(p._value.shape, jnp.float32),
                     "v": jnp.zeros(p._value.shape, jnp.float32),
                     "t": jnp.zeros((), jnp.float32)} for p in self._params]
        if self._update_fn is _functional_momentum:
            return [{"velocity": jnp.zeros_like(p._value)}
                    for p in self._params]
        return [{} for _ in self._params]

    def _build(self):
        params = self._params
        update_fn = self._update_fn
        hypers = self._hypers
        model = self.model
        loss_fn = self.loss_fn
        grad_clip = self.optimizer._grad_clip

        def compiled(p_values, opt_state, rng_key, lr, *inputs):
            def loss_of(pv):
                saved = [p._value for p in params]
                _generator.push_trace_key(rng_key)
                try:
                    for p, a in zip(params, pv):
                        p._value = a
                    with _tape.no_grad():
                        out = loss_fn(model, *[Tensor(i) for i in inputs])
                finally:
                    for p, s in zip(params, saved):
                        p._value = s
                    _generator.pop_trace_key()
                loss_t = out[0] if isinstance(out, tuple) else out
                aux = out[1:] if isinstance(out, tuple) else ()
                return loss_t._value, tuple(
                    a._value if isinstance(a, Tensor) else a for a in aux)

            (loss, aux), grads = jax.value_and_grad(
                loss_of, has_aux=True)(list(p_values))
            if grad_clip is not None and hasattr(grad_clip, "clip_norm"):
                gsq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                          for g in grads)
                gnorm = jnp.sqrt(gsq)
                cn = grad_clip.clip_norm
                scale = cn / jnp.maximum(gnorm, cn)
                grads = [g * scale.astype(g.dtype) for g in grads]
            new_p, new_s = [], []
            for p, g, s in zip(p_values, grads, opt_state):
                np_, ns_ = update_fn(p, g, s, lr, hypers)
                new_p.append(np_)
                new_s.append(ns_)
            return new_p, new_s, loss, aux

        jit_kwargs = dict(donate_argnums=(0, 1))
        self._compiled = jax.jit(compiled, **jit_kwargs)

    def __call__(self, *inputs):
        if self._state is None:
            self._state = self._init_state()
            self._build()
        arrays = [i._value if isinstance(i, Tensor) else jnp.asarray(i)
                  for i in inputs]
        key = _generator.default_generator().next_key()
        lr = jnp.float32(self.optimizer.get_lr())
        p_values = [p._value for p in self._params]
        new_p, self._state, loss, aux = self._compiled(
            p_values, self._state, key, lr, *arrays)
        for p, v in zip(self._params, new_p):
            p._value = v
        loss_t = Tensor(loss)
        if aux:
            return (loss_t,) + tuple(Tensor(a) for a in aux)
        return loss_t
