"""Dynamic-shape bucketing for jit compilation.

SURVEY §7 "hard parts": the reference re-runs InferShape per step so any
batch/sequence length works; XLA compiles per static shape.  The TPU
policy is bucketing — pad dynamic axes up to a small set of bucket sizes
so each bucket compiles once and every input reuses a cached executable.

``pad_to_bucket`` is the primitive; ``BucketedFunction`` wraps a jitted
callable with automatic padding + result cropping; padding masks let
losses ignore padded positions.
"""

from __future__ import annotations

from typing import Callable, Sequence

import numpy as np

from ..core.tensor import Tensor

__all__ = ["default_buckets", "pad_to_bucket", "BucketedFunction",
           "bucketed"]


def default_buckets(max_size: int, min_size: int = 8):
    """Power-of-two buckets up to max_size (the standard recompile-bound
    ladder: at most log2(max/min) executables per axis)."""
    buckets = []
    b = min_size
    while b < max_size:
        buckets.append(b)
        b *= 2
    buckets.append(max_size)
    return buckets


def _pick(size: int, buckets: Sequence[int]) -> int:
    for b in sorted(buckets):
        if size <= b:
            return int(b)
    raise ValueError(
        f"size {size} exceeds the largest bucket {max(buckets)}; widen the "
        "bucket ladder or truncate the input")


def pad_to_bucket(x, axis: int, buckets: Sequence[int], pad_value=0):
    """Pad ``x`` along ``axis`` up to the smallest bucket >= its size.
    Returns (padded_tensor, original_size, mask) where mask is 1.0 for
    real positions along that axis (shape: [bucket])."""
    import jax.numpy as jnp

    is_t = isinstance(x, Tensor)
    arr = x._value if is_t else jnp.asarray(x)
    size = arr.shape[axis]
    target = _pick(size, buckets)
    mask = jnp.asarray(
        (np.arange(target) < size).astype(np.float32))
    if target == size:
        return (x if is_t else Tensor(arr)), size, Tensor(mask)
    pad = [(0, 0)] * arr.ndim
    pad[axis] = (0, target - size)
    if is_t and not x.stop_gradient:
        # keep the tape linkage for differentiable inputs
        from ..core.dispatch import dispatch
        padded_t = dispatch(
            "bucket_pad",
            lambda a: jnp.pad(a, pad, constant_values=pad_value), (x,))
        return padded_t, size, Tensor(mask)
    padded = jnp.pad(arr, pad, constant_values=pad_value)
    return Tensor(padded), size, Tensor(mask)


class BucketedFunction:
    """Wraps fn so every call pads the chosen axes to bucket sizes before
    invoking (bounding the number of distinct compiled shapes) and crops
    outputs back to the true size.

    axes: {arg_index: (axis, buckets, pad_value)}.
    crop: None (no cropping) or (out_axis,) — crops every output Tensor of
    sufficient rank along that axis to the original (pre-pad) size of the
    lowest-indexed bucketed argument; lower-rank outputs (e.g. a scalar
    loss) pass through uncropped.
    """

    def __init__(self, fn: Callable, axes, crop=None):
        self.fn = fn
        self.axes = axes
        self.crop = crop
        self.compiled_shapes = set()

    def __call__(self, *args):
        args = list(args)
        true_size = None
        for idx in sorted(self.axes):
            axis, buckets, pad_value = self.axes[idx]
            args[idx], size, _ = pad_to_bucket(args[idx], axis, buckets,
                                               pad_value)
            if true_size is None:
                true_size = size
        shape_key = tuple(tuple(a.shape) if isinstance(a, Tensor) else None
                          for a in args)
        self.compiled_shapes.add(shape_key)
        out = self.fn(*args)
        if self.crop is None or true_size is None:
            return out
        (out_axis,) = self.crop

        def crop_one(t):
            if not isinstance(t, Tensor) or t.ndim <= out_axis:
                return t  # scalars/low-rank outputs (losses) pass through
            sl = [slice(None)] * t.ndim
            sl[out_axis] = slice(0, true_size)
            if not t.stop_gradient:
                # tape-recorded slice keeps gradients flowing to the fn
                from ..core.dispatch import dispatch
                return dispatch("bucket_crop",
                                lambda a: a[tuple(sl)], (t,))
            return Tensor(t._value[tuple(sl)])

        if isinstance(out, (tuple, list)):
            return type(out)(crop_one(o) for o in out)
        return crop_one(out)


def bucketed(axes, crop=None):
    """Decorator form: @bucketed({0: (1, default_buckets(2048), 0)})."""
    def wrap(fn):
        return BucketedFunction(fn, axes, crop)
    return wrap
