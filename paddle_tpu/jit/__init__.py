"""paddle_tpu.jit — dygraph-to-compiled (analogue of paddle.jit / to_static).

TPU-native design: instead of the reference's AST-transform + ProgramDesc +
run_program grad node pipeline (SURVEY §3.3), ``to_static`` traces the Python
function with jax.jit.  The compiled function is dispatched through the eager
tape as a single op, so ``loss.backward()`` differentiates *through* the
compiled region with a compiled transpose — functional parity with
RunProgramGradNode (``paddle/fluid/eager/to_static/run_program_op_node.h:314``)
at XLA-native speed.
"""

from .api import to_static, not_to_static, ignore_module, save, load, TranslatedLayer
from .train_step import TrainStep
from .bucketing import (BucketedFunction, bucketed, default_buckets,
                        pad_to_bucket)

__all__ = ["to_static", "not_to_static", "ignore_module", "save", "load",
           "TranslatedLayer", "TrainStep", "BucketedFunction", "bucketed",
           "default_buckets", "pad_to_bucket"]
