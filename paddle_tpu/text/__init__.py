"""paddle_tpu.text (analogue of ``python/paddle/text``: viterbi decode and
text dataset scaffolding; the reference's dataset downloads are gated on
network, here they raise with a clear message in this air-gapped build)."""

from .viterbi import viterbi_decode, ViterbiDecoder  # noqa: F401
from . import datasets  # noqa: F401

__all__ = ["viterbi_decode", "ViterbiDecoder", "datasets"]
