"""Text datasets (reference: ``python/paddle/text/datasets/{imdb.py,
uci_housing.py,conll05.py}``).  Zero-egress environment: synthetic data
with the reference datasets' shapes/label spaces, generated
deterministically — tokenized-sequence and regression pipelines exercise
the same code paths as the real downloads."""

from __future__ import annotations

import numpy as np

from ..io import Dataset

__all__ = ["Imdb", "UCIHousing", "Conll05st", "WMT14", "WMT16",
           "Movielens"]


class Imdb(Dataset):
    """Binary sentiment (reference ``python/paddle/text/datasets/imdb.py``).

    ``data_file`` given: parse the real aclImdb tar — tokenize the
    train split, build the frequency-cutoff word dict (ids ordered by
    descending frequency, last id = OOV like the reference), then encode
    the requested split; docs come back as variable-length int64 arrays,
    labels 0=pos 1=neg.  Without a path: synthetic token sequences with
    the real vocab size (this environment cannot download)."""

    vocab_size = 5149
    seq_len = 128

    def __init__(self, data_file=None, mode="train", cutoff=150, size=None,
                 seed=0):
        self.mode = mode
        if data_file:
            # one pass over the archive: tokenize train (for the dict)
            # and the requested split together
            token_docs = self._load_tokens(data_file, {"train", mode})
            self.word_idx = self._build_dict(token_docs["train"], cutoff)
            self.docs, self.labels = self._encode(token_docs[mode], mode,
                                                  data_file)
            self.size = len(self.docs)
            return
        self.word_idx = None
        self.size = (512 if mode == "train" else 128) if size is None else size
        rng = np.random.default_rng(seed + (0 if mode == "train" else 1))
        self.docs = rng.integers(1, self.vocab_size,
                                 (self.size, self.seq_len)).astype(np.int64)
        self.labels = rng.integers(0, 2, (self.size,)).astype(np.int64)
        # plant a weak signal so classifiers can learn: positive docs get
        # more of token 7
        mask = self.labels == 1
        self.docs[mask, :8] = 7

    @staticmethod
    def _tokenize(text):
        import re
        return re.sub(r"[^a-z ]", "",
                      text.lower().replace("<br />", " ")).split()

    def _load_tokens(self, data_file, splits):
        """ONE scan of the tar: {split: [(senti_label, tokens), ...]}."""
        import re
        import tarfile
        pat = re.compile(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$")
        out = {s: [] for s in splits}
        with tarfile.open(data_file, "r:*") as tf:
            for m in tf.getmembers():
                if not m.isfile():
                    continue
                match = pat.match(m.name)
                if not match or match.group(1) not in splits:
                    continue
                label = 0 if match.group(2) == "pos" else 1
                with tf.extractfile(m) as f:
                    out[match.group(1)].append(
                        (label, self._tokenize(
                            f.read().decode("utf-8", errors="ignore"))))
        return out

    @staticmethod
    def _build_dict(train_docs, cutoff):
        from collections import Counter
        freq = Counter()
        for _, tokens in train_docs:
            freq.update(tokens)
        # reference semantics: keep words with frequency > cutoff
        words = [w for w, c in freq.items() if c > cutoff]
        # most frequent word -> id 0 (reference sorts by -count)
        words.sort(key=lambda w: (-freq[w], w))
        idx = {w: i for i, w in enumerate(words)}
        idx["<unk>"] = len(idx)
        return idx

    def _encode(self, split_docs, mode, data_file):
        unk = self.word_idx["<unk>"]
        docs, labels = [], []
        # pos docs first, then neg (reference ordering)
        for label, tokens in sorted(split_docs, key=lambda lt: lt[0]):
            docs.append(np.asarray(
                [self.word_idx.get(t, unk) for t in tokens], np.int64))
            labels.append(label)
        if not docs:
            raise ValueError(
                f"Imdb: no aclImdb/{mode}/pos|neg/*.txt members in "
                f"{data_file}")
        return docs, np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return self.size


class UCIHousing(Dataset):
    """13-feature housing regression (reference feature count)."""

    feature_dim = 13

    def __init__(self, mode="train", size=None, seed=0):
        self.mode = mode
        self.size = (404 if mode == "train" else 102) if size is None else size
        rng = np.random.default_rng(seed + (0 if mode == "train" else 1))
        self.features = rng.standard_normal(
            (self.size, self.feature_dim)).astype(np.float32)
        w = rng.standard_normal(self.feature_dim).astype(np.float32)
        self.labels = (self.features @ w +
                       0.1 * rng.standard_normal(self.size)) \
            .astype(np.float32)[:, None]

    def __getitem__(self, idx):
        return self.features[idx], self.labels[idx]

    def __len__(self):
        return self.size


class Conll05st(Dataset):
    """SRL-style sequence labeling: (word_ids, predicate, label_ids)
    (reference conll05 schema, synthetic)."""

    word_dict_len = 44068
    label_dict_len = 59
    predicate_dict_len = 3162
    seq_len = 32

    def __init__(self, mode="train", size=None, seed=0):
        self.mode = mode
        self.size = (256 if mode == "train" else 64) if size is None else size
        rng = np.random.default_rng(seed + (0 if mode == "train" else 1))
        self.words = rng.integers(0, self.word_dict_len,
                                  (self.size, self.seq_len)).astype(np.int64)
        self.predicates = rng.integers(0, self.predicate_dict_len,
                                       (self.size,)).astype(np.int64)
        self.labels = rng.integers(0, self.label_dict_len,
                                   (self.size, self.seq_len)) \
            .astype(np.int64)

    def __getitem__(self, idx):
        return self.words[idx], self.predicates[idx], self.labels[idx]

    def __len__(self):
        return self.size


_WMT_START, _WMT_END, _WMT_UNK = "<s>", "<e>", "<unk>"
_WMT_UNK_IDX = 2


class WMT14(Dataset):
    """EN->FR translation pairs (reference
    ``python/paddle/text/datasets/wmt14.py``): items are
    ``(src_ids, trg_ids, trg_ids_next)`` int64 arrays; src wrapped in
    <s>...<e>, trg_ids starts with <s>, trg_ids_next ends with <e>;
    training pairs longer than 80 tokens are dropped.

    ``data_file`` given: parse the real tar (members ``*src.dict``,
    ``*trg.dict`` — one word per line, line number = id — and
    ``{mode}/{mode}`` with tab-separated sentence pairs).  Without a
    path: synthetic id sequences with the same marker conventions
    (zero-egress environment)."""

    def __init__(self, data_file=None, mode="train", dict_size=-1,
                 size=None, seed=0):
        if mode not in ("train", "test", "gen"):
            raise ValueError(
                f"mode should be 'train', 'test' or 'gen', got {mode}")
        self.mode = mode
        if data_file:
            if dict_size <= 0:
                raise ValueError("dict_size must be positive when parsing "
                                 "a real archive")
            self.dict_size = dict_size
            self._parse(data_file, mode, dict_size)
            return
        self.dict_size = dict_size if dict_size > 0 else 30000
        self.src_dict = self.trg_dict = None
        n = (512 if mode == "train" else 128) if size is None else size
        rng = np.random.default_rng(
            seed + {"train": 0, "test": 1, "gen": 2}[mode])
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        for _ in range(n):
            ls, lt = int(rng.integers(4, 30)), int(rng.integers(4, 30))
            src = rng.integers(3, self.dict_size, ls)
            trg = rng.integers(3, self.dict_size, lt)
            self.src_ids.append(
                np.concatenate([[0], src, [1]]).astype(np.int64))
            self.trg_ids.append(
                np.concatenate([[0], trg]).astype(np.int64))
            self.trg_ids_next.append(
                np.concatenate([trg, [1]]).astype(np.int64))

    def _parse(self, data_file, mode, dict_size):
        import tarfile

        def to_dict(fd, size):
            out = {}
            for i, line in enumerate(fd):
                if i >= size:
                    break
                out[line.strip().decode()] = i
            return out

        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(data_file, "r:*") as tf:
            members = [m.name for m in tf.getmembers() if m.isfile()]

            def one(suffix):
                names = [n for n in members if n.endswith(suffix)]
                if len(names) != 1:
                    raise ValueError(
                        f"WMT14: expected exactly one member ending "
                        f"'{suffix}' in {data_file}, found {names}")
                return names[0]

            self.src_dict = to_dict(tf.extractfile(one("src.dict")),
                                    dict_size)
            self.trg_dict = to_dict(tf.extractfile(one("trg.dict")),
                                    dict_size)
            for line in tf.extractfile(one(f"{mode}/{mode}")):
                parts = line.decode().strip().split("\t")
                if len(parts) != 2:
                    continue
                src = [self.src_dict.get(w, _WMT_UNK_IDX)
                       for w in ([_WMT_START] + parts[0].split()
                                 + [_WMT_END])]
                trg = [self.trg_dict.get(w, _WMT_UNK_IDX)
                       for w in parts[1].split()]
                if len(src) > 80 or len(trg) > 80:
                    continue
                self.src_ids.append(np.asarray(src, np.int64))
                self.trg_ids.append(np.asarray(
                    [self.trg_dict[_WMT_START]] + trg, np.int64))
                self.trg_ids_next.append(np.asarray(
                    trg + [self.trg_dict[_WMT_END]], np.int64))

    def get_dict(self, reverse=False):
        if self.src_dict is None:
            raise ValueError("synthetic WMT14 has no word dictionaries")
        if reverse:
            return ({v: k for k, v in self.src_dict.items()},
                    {v: k for k, v in self.trg_dict.items()})
        return self.src_dict, self.trg_dict

    def __getitem__(self, idx):
        return (self.src_ids[idx], self.trg_ids[idx],
                self.trg_ids_next[idx])

    def __len__(self):
        return len(self.src_ids)


class WMT16(Dataset):
    """EN<->DE translation (reference
    ``python/paddle/text/datasets/wmt16.py``): same item triple as WMT14;
    dictionaries are BUILT from the training split by descending
    frequency with <s>/<e>/<unk> as ids 0/1/2; ``lang`` picks the source
    column.  Archive layout: ``wmt16/{train,test,val}``, tab-separated
    en<TAB>de lines."""

    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en", size=None, seed=0):
        if mode not in ("train", "test", "val"):
            raise ValueError(
                f"mode should be 'train', 'test' or 'val', got {mode}")
        if lang not in ("en", "de"):
            raise ValueError(f"lang should be 'en' or 'de', got {lang}")
        self.mode = mode
        self.lang = lang
        if data_file:
            if src_dict_size <= 0 or trg_dict_size <= 0:
                raise ValueError("src/trg_dict_size must be positive when "
                                 "parsing a real archive")
            import tarfile
            with tarfile.open(data_file, "r:*") as tf:
                en_dict, de_dict = self._build_dicts(
                    tf, src_dict_size if lang == "en" else trg_dict_size,
                    trg_dict_size if lang == "en" else src_dict_size)
                self.src_dict = en_dict if lang == "en" else de_dict
                self.trg_dict = de_dict if lang == "en" else en_dict
                self._load(tf, mode)
            return
        self.src_dict = self.trg_dict = None
        self.src_dict_size = src_dict_size if src_dict_size > 0 else 10000
        self.trg_dict_size = trg_dict_size if trg_dict_size > 0 else 10000
        n = (512 if mode == "train" else 128) if size is None else size
        rng = np.random.default_rng(
            seed + {"train": 0, "test": 1, "val": 2}[mode]
            + (0 if lang == "en" else 3))
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        for _ in range(n):
            ls, lt = int(rng.integers(4, 30)), int(rng.integers(4, 30))
            src = rng.integers(3, self.src_dict_size, ls)
            trg = rng.integers(3, self.trg_dict_size, lt)
            self.src_ids.append(
                np.concatenate([[0], src, [1]]).astype(np.int64))
            self.trg_ids.append(
                np.concatenate([[0], trg]).astype(np.int64))
            self.trg_ids_next.append(
                np.concatenate([trg, [1]]).astype(np.int64))

    @staticmethod
    def _build_dicts(tf, en_dict_size, de_dict_size):
        """Both language dictionaries from ONE pass over wmt16/train
        (the train split is the big member; decompress it once)."""
        from collections import Counter
        en_freq, de_freq = Counter(), Counter()
        for line in tf.extractfile("wmt16/train"):
            parts = line.decode().strip().split("\t")
            if len(parts) != 2:
                continue
            en_freq.update(parts[0].split())
            de_freq.update(parts[1].split())

        def to_dict(freq, dict_size):
            words = [_WMT_START, _WMT_END, _WMT_UNK]
            words += [w for w, _ in sorted(freq.items(),
                                           key=lambda kv: (-kv[1], kv[0]))]
            return {w: i for i, w in enumerate(words[:dict_size])}

        return to_dict(en_freq, en_dict_size), to_dict(de_freq,
                                                       de_dict_size)

    def _load(self, tf, mode):
        start_id = self.src_dict[_WMT_START]
        end_id = self.src_dict[_WMT_END]
        unk_id = self.src_dict[_WMT_UNK]
        src_col = 0 if self.lang == "en" else 1
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        for line in tf.extractfile(f"wmt16/{mode}"):
            parts = line.decode().strip().split("\t")
            if len(parts) != 2:
                continue
            src = [self.src_dict.get(w, unk_id)
                   for w in parts[src_col].split()]
            trg = [self.trg_dict.get(w, unk_id)
                   for w in parts[1 - src_col].split()]
            self.src_ids.append(np.asarray(
                [start_id] + src + [end_id], np.int64))
            self.trg_ids.append(np.asarray([start_id] + trg, np.int64))
            self.trg_ids_next.append(np.asarray(trg + [end_id],
                                                np.int64))

    def get_dict(self, lang, reverse=False):
        if self.src_dict is None:
            raise ValueError("synthetic WMT16 has no word dictionaries")
        d = self.src_dict if lang == self.lang else self.trg_dict
        return {v: k for k, v in d.items()} if reverse else d

    def __getitem__(self, idx):
        return (self.src_ids[idx], self.trg_ids[idx],
                self.trg_ids_next[idx])

    def __len__(self):
        return len(self.src_ids)


class Movielens(Dataset):
    """ml-1m rating prediction (reference
    ``python/paddle/text/datasets/movielens.py``): each item is the
    8-field tuple ``([uid], [gender], [age_idx], [job], [mov_id],
    [category_ids...], [title_ids...], [rating])`` with rating rescaled
    to ``stars*2-5``; train/test split by a seeded random draw per
    rating line (reference semantics).

    ``data_file``: the real ml-1m zip (movies.dat/users.dat/ratings.dat,
    ``::``-separated, latin-1).  Without a path: synthetic rows with the
    real id spaces."""

    age_table = [1, 18, 25, 35, 45, 50, 56]

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, size=None):
        if mode not in ("train", "test"):
            raise ValueError(f"mode should be 'train' or 'test', got {mode}")
        self.mode = mode
        if data_file:
            self._parse(data_file, mode, test_ratio, rand_seed)
            return
        n = (1024 if mode == "train" else 128) if size is None else size
        rng = np.random.default_rng(rand_seed + (mode == "test"))
        self.data = []
        for _ in range(n):
            n_cat = int(rng.integers(1, 4))
            n_title = int(rng.integers(1, 5))
            self.data.append((
                np.asarray([rng.integers(1, 6041)], np.int64),
                np.asarray([rng.integers(0, 2)], np.int64),
                np.asarray([rng.integers(0, len(self.age_table))],
                           np.int64),
                np.asarray([rng.integers(0, 21)], np.int64),
                np.asarray([rng.integers(1, 3953)], np.int64),
                rng.integers(0, 18, n_cat).astype(np.int64),
                rng.integers(0, 5000, n_title).astype(np.int64),
                np.asarray([float(rng.integers(1, 6)) * 2 - 5.0],
                           np.float32),
            ))

    def _parse(self, data_file, mode, test_ratio, rand_seed):
        import re
        import zipfile
        pattern = re.compile(r"^(.*)\((\d+)\)$")
        movies, users = {}, {}
        title_words, categories = set(), set()
        with zipfile.ZipFile(data_file) as z:
            with z.open("ml-1m/movies.dat") as f:
                for line in f:
                    mid, title, cats = line.decode("latin1").strip() \
                        .split("::")
                    cats = cats.split("|")
                    categories.update(cats)
                    m = pattern.match(title)
                    title = m.group(1) if m else title
                    movies[int(mid)] = (int(mid), title, cats)
                    title_words.update(w.lower() for w in title.split())
            title_dict = {w: i for i, w in enumerate(sorted(title_words))}
            cat_dict = {c: i for i, c in enumerate(sorted(categories))}
            with z.open("ml-1m/users.dat") as f:
                for line in f:
                    uid, gender, age, job = line.decode("latin1").strip() \
                        .split("::")[:4]
                    users[int(uid)] = (
                        int(uid), 0 if gender == "M" else 1,
                        self.age_table.index(int(age)), int(job))
            rng = np.random.default_rng(rand_seed)
            is_test = mode == "test"
            self.data = []
            with z.open("ml-1m/ratings.dat") as f:
                for line in f:
                    if (rng.random() < test_ratio) != is_test:
                        continue
                    uid, mid, stars = line.decode("latin1").strip() \
                        .split("::")[:3]
                    u = users[int(uid)]
                    mid_i, title, cats = movies[int(mid)]
                    self.data.append((
                        np.asarray([u[0]], np.int64),
                        np.asarray([u[1]], np.int64),
                        np.asarray([u[2]], np.int64),
                        np.asarray([u[3]], np.int64),
                        np.asarray([mid_i], np.int64),
                        np.asarray([cat_dict[c] for c in cats], np.int64),
                        np.asarray([title_dict[w.lower()]
                                    for w in title.split()], np.int64),
                        np.asarray([float(stars) * 2 - 5.0], np.float32),
                    ))

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)
