"""Text datasets (reference: ``python/paddle/text/datasets/{imdb.py,
uci_housing.py,conll05.py}``).  Zero-egress environment: synthetic data
with the reference datasets' shapes/label spaces, generated
deterministically — tokenized-sequence and regression pipelines exercise
the same code paths as the real downloads."""

from __future__ import annotations

import numpy as np

from ..io import Dataset

__all__ = ["Imdb", "UCIHousing", "Conll05st"]


class Imdb(Dataset):
    """Binary sentiment (reference ``python/paddle/text/datasets/imdb.py``).

    ``data_file`` given: parse the real aclImdb tar — tokenize the
    train split, build the frequency-cutoff word dict (ids ordered by
    descending frequency, last id = OOV like the reference), then encode
    the requested split; docs come back as variable-length int64 arrays,
    labels 0=pos 1=neg.  Without a path: synthetic token sequences with
    the real vocab size (this environment cannot download)."""

    vocab_size = 5149
    seq_len = 128

    def __init__(self, data_file=None, mode="train", cutoff=150, size=None,
                 seed=0):
        self.mode = mode
        if data_file:
            # one pass over the archive: tokenize train (for the dict)
            # and the requested split together
            token_docs = self._load_tokens(data_file, {"train", mode})
            self.word_idx = self._build_dict(token_docs["train"], cutoff)
            self.docs, self.labels = self._encode(token_docs[mode], mode,
                                                  data_file)
            self.size = len(self.docs)
            return
        self.word_idx = None
        self.size = (512 if mode == "train" else 128) if size is None else size
        rng = np.random.default_rng(seed + (0 if mode == "train" else 1))
        self.docs = rng.integers(1, self.vocab_size,
                                 (self.size, self.seq_len)).astype(np.int64)
        self.labels = rng.integers(0, 2, (self.size,)).astype(np.int64)
        # plant a weak signal so classifiers can learn: positive docs get
        # more of token 7
        mask = self.labels == 1
        self.docs[mask, :8] = 7

    @staticmethod
    def _tokenize(text):
        import re
        return re.sub(r"[^a-z ]", "",
                      text.lower().replace("<br />", " ")).split()

    def _load_tokens(self, data_file, splits):
        """ONE scan of the tar: {split: [(senti_label, tokens), ...]}."""
        import re
        import tarfile
        pat = re.compile(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$")
        out = {s: [] for s in splits}
        with tarfile.open(data_file, "r:*") as tf:
            for m in tf.getmembers():
                if not m.isfile():
                    continue
                match = pat.match(m.name)
                if not match or match.group(1) not in splits:
                    continue
                label = 0 if match.group(2) == "pos" else 1
                with tf.extractfile(m) as f:
                    out[match.group(1)].append(
                        (label, self._tokenize(
                            f.read().decode("utf-8", errors="ignore"))))
        return out

    @staticmethod
    def _build_dict(train_docs, cutoff):
        from collections import Counter
        freq = Counter()
        for _, tokens in train_docs:
            freq.update(tokens)
        # reference semantics: keep words with frequency > cutoff
        words = [w for w, c in freq.items() if c > cutoff]
        # most frequent word -> id 0 (reference sorts by -count)
        words.sort(key=lambda w: (-freq[w], w))
        idx = {w: i for i, w in enumerate(words)}
        idx["<unk>"] = len(idx)
        return idx

    def _encode(self, split_docs, mode, data_file):
        unk = self.word_idx["<unk>"]
        docs, labels = [], []
        # pos docs first, then neg (reference ordering)
        for label, tokens in sorted(split_docs, key=lambda lt: lt[0]):
            docs.append(np.asarray(
                [self.word_idx.get(t, unk) for t in tokens], np.int64))
            labels.append(label)
        if not docs:
            raise ValueError(
                f"Imdb: no aclImdb/{mode}/pos|neg/*.txt members in "
                f"{data_file}")
        return docs, np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return self.size


class UCIHousing(Dataset):
    """13-feature housing regression (reference feature count)."""

    feature_dim = 13

    def __init__(self, mode="train", size=None, seed=0):
        self.mode = mode
        self.size = (404 if mode == "train" else 102) if size is None else size
        rng = np.random.default_rng(seed + (0 if mode == "train" else 1))
        self.features = rng.standard_normal(
            (self.size, self.feature_dim)).astype(np.float32)
        w = rng.standard_normal(self.feature_dim).astype(np.float32)
        self.labels = (self.features @ w +
                       0.1 * rng.standard_normal(self.size)) \
            .astype(np.float32)[:, None]

    def __getitem__(self, idx):
        return self.features[idx], self.labels[idx]

    def __len__(self):
        return self.size


class Conll05st(Dataset):
    """SRL-style sequence labeling: (word_ids, predicate, label_ids)
    (reference conll05 schema, synthetic)."""

    word_dict_len = 44068
    label_dict_len = 59
    predicate_dict_len = 3162
    seq_len = 32

    def __init__(self, mode="train", size=None, seed=0):
        self.mode = mode
        self.size = (256 if mode == "train" else 64) if size is None else size
        rng = np.random.default_rng(seed + (0 if mode == "train" else 1))
        self.words = rng.integers(0, self.word_dict_len,
                                  (self.size, self.seq_len)).astype(np.int64)
        self.predicates = rng.integers(0, self.predicate_dict_len,
                                       (self.size,)).astype(np.int64)
        self.labels = rng.integers(0, self.label_dict_len,
                                   (self.size, self.seq_len)) \
            .astype(np.int64)

    def __getitem__(self, idx):
        return self.words[idx], self.predicates[idx], self.labels[idx]

    def __len__(self):
        return self.size
