"""Text datasets (reference: ``python/paddle/text/datasets/{imdb.py,
uci_housing.py,conll05.py}``).  Zero-egress environment: synthetic data
with the reference datasets' shapes/label spaces, generated
deterministically — tokenized-sequence and regression pipelines exercise
the same code paths as the real downloads."""

from __future__ import annotations

import numpy as np

from ..io import Dataset

__all__ = ["Imdb", "UCIHousing", "Conll05st"]


class Imdb(Dataset):
    """Binary sentiment over token-id sequences (vocab 5149 like the
    real IMDB vocabulary after cutoff; fixed-length padded)."""

    vocab_size = 5149
    seq_len = 128

    def __init__(self, mode="train", cutoff=150, size=None, seed=0):
        self.mode = mode
        self.size = (512 if mode == "train" else 128) if size is None else size
        rng = np.random.default_rng(seed + (0 if mode == "train" else 1))
        self.docs = rng.integers(1, self.vocab_size,
                                 (self.size, self.seq_len)).astype(np.int64)
        self.labels = rng.integers(0, 2, (self.size,)).astype(np.int64)
        # plant a weak signal so classifiers can learn: positive docs get
        # more of token 7
        mask = self.labels == 1
        self.docs[mask, :8] = 7

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return self.size


class UCIHousing(Dataset):
    """13-feature housing regression (reference feature count)."""

    feature_dim = 13

    def __init__(self, mode="train", size=None, seed=0):
        self.mode = mode
        self.size = (404 if mode == "train" else 102) if size is None else size
        rng = np.random.default_rng(seed + (0 if mode == "train" else 1))
        self.features = rng.standard_normal(
            (self.size, self.feature_dim)).astype(np.float32)
        w = rng.standard_normal(self.feature_dim).astype(np.float32)
        self.labels = (self.features @ w +
                       0.1 * rng.standard_normal(self.size)) \
            .astype(np.float32)[:, None]

    def __getitem__(self, idx):
        return self.features[idx], self.labels[idx]

    def __len__(self):
        return self.size


class Conll05st(Dataset):
    """SRL-style sequence labeling: (word_ids, predicate, label_ids)
    (reference conll05 schema, synthetic)."""

    word_dict_len = 44068
    label_dict_len = 59
    predicate_dict_len = 3162
    seq_len = 32

    def __init__(self, mode="train", size=None, seed=0):
        self.mode = mode
        self.size = (256 if mode == "train" else 64) if size is None else size
        rng = np.random.default_rng(seed + (0 if mode == "train" else 1))
        self.words = rng.integers(0, self.word_dict_len,
                                  (self.size, self.seq_len)).astype(np.int64)
        self.predicates = rng.integers(0, self.predicate_dict_len,
                                       (self.size,)).astype(np.int64)
        self.labels = rng.integers(0, self.label_dict_len,
                                   (self.size, self.seq_len)) \
            .astype(np.int64)

    def __getitem__(self, idx):
        return self.words[idx], self.predicates[idx], self.labels[idx]

    def __len__(self):
        return self.size
