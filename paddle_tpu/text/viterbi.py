"""Viterbi decoding (reference: ``python/paddle/text/viterbi_decode.py``
over ``paddle/phi/kernels/cpu/viterbi_decode_kernel.cc``).

TPU-native: the DP recursion is a ``lax.scan`` over time steps (static
shapes, no host loop), scores+paths returned like the reference op.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import dispatch
from ..nn import Layer

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """potentials [B, T, N]; transition [N, N]; lengths [B].

    Returns (scores [B], paths [B, T]).  Positions past each sequence
    length hold the last valid tag (reference pads with the final state).
    """

    def impl(emit, trans, lens):
        b, t, n = emit.shape
        if include_bos_eos_tag:
            # reference semantics (python/paddle/text/viterbi_decode.py):
            # the LAST row/column of transitions is the start tag, the
            # second-to-last the stop tag
            start_idx, stop_idx = n - 1, n - 2
            init = emit[:, 0] + trans[start_idx][None, :]
        else:
            init = emit[:, 0]

        def step(carry, e_t):
            alpha, tstep = carry
            # alpha [B, N]; scores [B, N(from), N(to)]
            scores = alpha[:, :, None] + trans[None, :, :]
            best_from = jnp.argmax(scores, axis=1)          # [B, N]
            best_score = jnp.max(scores, axis=1) + e_t      # [B, N]
            # only advance sequences that still have tokens
            active = (tstep < lens)[:, None]
            alpha_new = jnp.where(active, best_score, alpha)
            return (alpha_new, tstep + 1), (best_from, active)

        (alpha, _), (backptr, actives) = jax.lax.scan(
            step, (init, jnp.ones((), jnp.int32)),
            jnp.swapaxes(emit[:, 1:], 0, 1))
        if include_bos_eos_tag:
            alpha = alpha + trans[:, stop_idx][None, :]
        scores = jnp.max(alpha, axis=-1)
        last = jnp.argmax(alpha, axis=-1)                    # [B]

        def back(tag, inp):
            # reverse scan: carry is the tag at step i+1, output it, and
            # step back through the pointer to the tag at step i
            ptr, active = inp
            prev = jnp.take_along_axis(ptr, tag[:, None], axis=1)[:, 0]
            return jnp.where(active[:, 0], prev, tag), tag

        tag0, path_rev = jax.lax.scan(back, last, (backptr, actives),
                                      reverse=True)
        # path_rev[i] = tag at step i+1 (original order); prepend step 0
        paths = jnp.concatenate([tag0[:, None],
                                 jnp.swapaxes(path_rev, 0, 1)], axis=1)
        return scores, paths.astype(jnp.int64)

    return dispatch("viterbi_decode", impl,
                    (potentials, transition_params, lengths),
                    nondiff_mask=[True, True, True], n_diff_outputs=0)


class ViterbiDecoder(Layer):
    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
