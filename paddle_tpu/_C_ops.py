"""paddle_tpu._C_ops — the low-level op namespace.

Analogue of ``python/paddle/_C_ops.py:20`` (which re-exports the generated
``core.eager.ops``). Every op in the registry (paddle_tpu/ops/ops.yaml) is
reachable here by name, giving reference users their accustomed
``_C_ops.matmul(x, y)`` escape hatch. Resolution is lazy per attribute so
importing this module costs nothing.
"""

from __future__ import annotations

from .ops import registry as _registry


def __getattr__(name: str):
    specs = _registry.registry_by_name()
    if name in specs:
        fn = _registry.resolve(specs[name])
        globals()[name] = fn  # cache for next access
        return fn
    raise AttributeError(f"_C_ops has no op {name!r} "
                         "(not in paddle_tpu/ops/ops.yaml)")


def __dir__():
    return sorted(_registry.registry_by_name())
