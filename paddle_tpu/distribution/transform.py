"""Bijective transforms + TransformedDistribution
(≙ python/paddle/distribution/transform.py, transformed_distribution.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .distribution import Distribution, _arr


class Transform:
    def forward(self, x):
        return Tensor(self._forward(_arr(x)))

    def inverse(self, y):
        return Tensor(self._inverse(_arr(y)))

    def forward_log_det_jacobian(self, x):
        return Tensor(self._fldj(_arr(x)))

    def inverse_log_det_jacobian(self, y):
        return Tensor(-self._fldj(self._inverse(_arr(y))))

    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _fldj(self, x):
        raise NotImplementedError


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _fldj(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        return x


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _fldj(self, x):
        return jax.nn.log_sigmoid(x) + jax.nn.log_sigmoid(-x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _fldj(self, x):
        # log(1 - tanh(x)^2) = 2*(log2 - x - softplus(-2x))
        return 2.0 * (jnp.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _fldj(self, x):
        total = 0.0
        for t in self.transforms:
            total = total + t._fldj(x)
            x = t._forward(x)
        return total


class TransformedDistribution(Distribution):
    def __init__(self, base: Distribution, transforms):
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.base = base
        self.transform = (transforms[0] if len(transforms) == 1
                          else ChainTransform(transforms))
        super().__init__(base._batch_shape, base._event_shape)

    def _sample(self, shape):
        return self.transform._forward(self.base._sample(shape))

    def _log_prob(self, v):
        x = self.transform._inverse(v)
        return self.base._log_prob(x) - self.transform._fldj(x)
