"""paddle_tpu.distribution — probability distributions.

Analogue of ``python/paddle/distribution/`` (SURVEY §2.9: ~20 distributions,
transforms, KL registry). Distributions are Tensor-in/Tensor-out; sampling
draws keys from the global Generator so it composes with paddle.seed and
stays jit-traceable under to_static (counter-based PRNG).
"""

from .distribution import (  # noqa: F401
    Distribution, Normal, Uniform, Bernoulli, Categorical, Multinomial,
    Beta, Gamma, Dirichlet, Exponential, Laplace, LogNormal, Cauchy,
    Geometric, Gumbel, Poisson, StudentT, Binomial,
)
from .kl import kl_divergence, register_kl  # noqa: F401
from .transform import (  # noqa: F401
    Transform, AffineTransform, ExpTransform, SigmoidTransform,
    TanhTransform, ChainTransform, TransformedDistribution,
)

__all__ = [
    "Distribution", "Normal", "Uniform", "Bernoulli", "Categorical",
    "Multinomial", "Beta", "Gamma", "Dirichlet", "Exponential", "Laplace",
    "LogNormal", "Cauchy", "Geometric", "Gumbel", "Poisson", "StudentT",
    "Binomial", "kl_divergence", "register_kl", "Transform",
    "AffineTransform", "ExpTransform", "SigmoidTransform", "TanhTransform",
    "ChainTransform", "TransformedDistribution",
]
