"""Distribution implementations (≙ python/paddle/distribution/*.py).

Each distribution stores broadcast parameters as jax arrays and exposes the
reference surface: sample/rsample, log_prob/prob, entropy, mean/variance,
cdf where standard. Reparameterized sampling (rsample) is provided where
the pathwise gradient is well-defined.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp_special

from ..core import generator as _generator
from ..core.tensor import Tensor


def _arr(x, dtype=None):
    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    if dtype is not None and v.dtype != dtype:
        v = v.astype(dtype)
    if jnp.issubdtype(v.dtype, jnp.integer):
        v = v.astype(jnp.float32)
    return v


def _key():
    return _generator.default_generator().next_key()


def _shape(sample_shape, batch_shape, event_shape=()):
    return tuple(sample_shape) + tuple(batch_shape) + tuple(event_shape)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return list(self._batch_shape)

    @property
    def event_shape(self):
        return list(self._event_shape)

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape: Sequence[int] = ()):
        return Tensor(jax.lax.stop_gradient(self._sample(tuple(shape))))

    def rsample(self, shape: Sequence[int] = ()):
        return Tensor(self._sample(tuple(shape)))

    def log_prob(self, value):
        return Tensor(self._log_prob(_arr(value)))

    def prob(self, value):
        return Tensor(jnp.exp(self._log_prob(_arr(value))))

    def entropy(self):
        return Tensor(self._entropy())

    def _sample(self, shape):
        raise NotImplementedError

    def _log_prob(self, value):
        raise NotImplementedError

    def _entropy(self):
        raise NotImplementedError


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self._batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(self.scale ** 2, self._batch_shape))

    def _sample(self, shape):
        eps = jax.random.normal(_key(), _shape(shape, self._batch_shape))
        return self.loc + self.scale * eps

    def _log_prob(self, v):
        var = self.scale ** 2
        return -((v - self.loc) ** 2) / (2 * var) - jnp.log(self.scale) \
            - 0.5 * math.log(2 * math.pi)

    def _entropy(self):
        out = 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)
        return jnp.broadcast_to(out, self._batch_shape)

    def cdf(self, value):
        z = (_arr(value) - self.loc) / (self.scale * math.sqrt(2))
        return Tensor(0.5 * (1 + jax.scipy.special.erf(z)))

    def kl_divergence(self, other: "Normal"):
        from .kl import kl_divergence
        return kl_divergence(self, other)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _arr(low)
        self.high = _arr(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape,
                                              self.high.shape))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to((self.low + self.high) / 2,
                                       self._batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to((self.high - self.low) ** 2 / 12,
                                       self._batch_shape))

    def _sample(self, shape):
        u = jax.random.uniform(_key(), _shape(shape, self._batch_shape))
        return self.low + (self.high - self.low) * u

    def _log_prob(self, v):
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return jnp.where(inside, lp, -jnp.inf)

    def _entropy(self):
        return jnp.broadcast_to(jnp.log(self.high - self.low),
                                self._batch_shape)


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs/logits")
        if probs is not None:
            self.probs = _arr(probs)
            self.logits = jnp.log(self.probs) - jnp.log1p(-self.probs)
        else:
            self.logits = _arr(logits)
            self.probs = jax.nn.sigmoid(self.logits)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return Tensor(self.probs)

    @property
    def variance(self):
        return Tensor(self.probs * (1 - self.probs))

    def _sample(self, shape):
        return jax.random.bernoulli(
            _key(), self.probs, _shape(shape, self._batch_shape)
        ).astype(jnp.float32)

    def _log_prob(self, v):
        return v * jax.nn.log_sigmoid(self.logits) + \
            (1 - v) * jax.nn.log_sigmoid(-self.logits)

    def _entropy(self):
        p = self.probs
        return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        # reference Categorical(logits=unnormalized probs); accept both
        if logits is not None:
            arr = _arr(logits)
            # reference treats `logits` as unnormalized nonneg scores only if
            # explicitly probabilities; standard interpretation: log-space
            self.logits = jax.nn.log_softmax(arr, axis=-1)
        else:
            p = _arr(probs)
            self.logits = jnp.log(p / jnp.sum(p, -1, keepdims=True))
        self.probs = jnp.exp(self.logits)
        super().__init__(self.logits.shape[:-1])
        self._n = self.logits.shape[-1]

    @property
    def mean(self):  # undefined; parity with reference raising
        raise NotImplementedError("Categorical has no mean")

    def _sample(self, shape):
        return jax.random.categorical(
            _key(), self.logits, shape=_shape(shape, self._batch_shape))

    def _log_prob(self, v):
        idx = v.astype(jnp.int32)
        return jnp.take_along_axis(
            jnp.broadcast_to(self.logits, idx.shape + (self._n,)),
            idx[..., None], axis=-1)[..., 0]

    def _entropy(self):
        return -jnp.sum(self.probs * self.logits, axis=-1)


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs = _arr(probs)
        self.probs = self.probs / jnp.sum(self.probs, -1, keepdims=True)
        super().__init__(self.probs.shape[:-1], self.probs.shape[-1:])

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs)

    @property
    def variance(self):
        return Tensor(self.total_count * self.probs * (1 - self.probs))

    def _sample(self, shape):
        logits = jnp.log(self.probs)
        draws = jax.random.categorical(
            _key(), logits,
            shape=(self.total_count,) + _shape(shape, self._batch_shape))
        n = self.probs.shape[-1]
        counts = jax.nn.one_hot(draws, n).sum(0)
        return counts

    def _log_prob(self, v):
        logits = jnp.log(self.probs)
        return (jsp_special.gammaln(self.total_count + 1.0)
                - jnp.sum(jsp_special.gammaln(v + 1.0), -1)
                + jnp.sum(v * logits, -1))


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _arr(alpha)
        self.beta = _arr(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape,
                                              self.beta.shape))

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        s = self.alpha + self.beta
        return Tensor(self.alpha * self.beta / (s * s * (s + 1)))

    def _sample(self, shape):
        return jax.random.beta(_key(), self.alpha, self.beta,
                               _shape(shape, self._batch_shape))

    def _log_prob(self, v):
        return ((self.alpha - 1) * jnp.log(v) +
                (self.beta - 1) * jnp.log1p(-v) -
                (jsp_special.gammaln(self.alpha) +
                 jsp_special.gammaln(self.beta) -
                 jsp_special.gammaln(self.alpha + self.beta)))

    def _entropy(self):
        a, b = self.alpha, self.beta
        lbeta = (jsp_special.gammaln(a) + jsp_special.gammaln(b)
                 - jsp_special.gammaln(a + b))
        return (lbeta - (a - 1) * jsp_special.digamma(a)
                - (b - 1) * jsp_special.digamma(b)
                + (a + b - 2) * jsp_special.digamma(a + b))


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _arr(concentration)
        self.rate = _arr(rate)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape,
                                              self.rate.shape))

    @property
    def mean(self):
        return Tensor(self.concentration / self.rate)

    @property
    def variance(self):
        return Tensor(self.concentration / self.rate ** 2)

    def _sample(self, shape):
        g = jax.random.gamma(_key(), self.concentration,
                             _shape(shape, self._batch_shape))
        return g / self.rate

    def _log_prob(self, v):
        a, r = self.concentration, self.rate
        return (a * jnp.log(r) + (a - 1) * jnp.log(v) - r * v
                - jsp_special.gammaln(a))

    def _entropy(self):
        a, r = self.concentration, self.rate
        return (a - jnp.log(r) + jsp_special.gammaln(a)
                + (1 - a) * jsp_special.digamma(a))


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _arr(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    @property
    def mean(self):
        return Tensor(self.concentration /
                      jnp.sum(self.concentration, -1, keepdims=True))

    def _sample(self, shape):
        return jax.random.dirichlet(_key(), self.concentration,
                                    _shape(shape, self._batch_shape))

    def _log_prob(self, v):
        a = self.concentration
        return (jnp.sum((a - 1) * jnp.log(v), -1)
                + jsp_special.gammaln(jnp.sum(a, -1))
                - jnp.sum(jsp_special.gammaln(a), -1))


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _arr(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return Tensor(1.0 / self.rate)

    @property
    def variance(self):
        return Tensor(self.rate ** -2)

    def _sample(self, shape):
        e = jax.random.exponential(_key(), _shape(shape, self._batch_shape))
        return e / self.rate

    def _log_prob(self, v):
        return jnp.log(self.rate) - self.rate * v

    def _entropy(self):
        return jnp.broadcast_to(1 - jnp.log(self.rate), self._batch_shape)


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self._batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(2 * self.scale ** 2,
                                       self._batch_shape))

    def _sample(self, shape):
        u = jax.random.laplace(_key(), _shape(shape, self._batch_shape))
        return self.loc + self.scale * u

    def _log_prob(self, v):
        return -jnp.abs(v - self.loc) / self.scale - \
            jnp.log(2 * self.scale)

    def _entropy(self):
        return jnp.broadcast_to(1 + jnp.log(2 * self.scale),
                                self._batch_shape)


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.exp(self.loc + self.scale ** 2 / 2))

    @property
    def variance(self):
        s2 = self.scale ** 2
        return Tensor((jnp.exp(s2) - 1) * jnp.exp(2 * self.loc + s2))

    def _sample(self, shape):
        eps = jax.random.normal(_key(), _shape(shape, self._batch_shape))
        return jnp.exp(self.loc + self.scale * eps)

    def _log_prob(self, v):
        logv = jnp.log(v)
        return (-((logv - self.loc) ** 2) / (2 * self.scale ** 2)
                - logv - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def _entropy(self):
        return self.loc + 0.5 + 0.5 * math.log(2 * math.pi) + \
            jnp.log(self.scale)


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def _sample(self, shape):
        return jax.random.cauchy(
            _key(), _shape(shape, self._batch_shape)) * self.scale + self.loc

    def _log_prob(self, v):
        z = (v - self.loc) / self.scale
        return -jnp.log(math.pi * self.scale * (1 + z * z))

    def _entropy(self):
        return jnp.broadcast_to(jnp.log(4 * math.pi * self.scale),
                                self._batch_shape)


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p for k = 0, 1, ... (failures before first success)."""

    def __init__(self, probs, name=None):
        self.probs = _arr(probs)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return Tensor((1 - self.probs) / self.probs)

    @property
    def variance(self):
        return Tensor((1 - self.probs) / self.probs ** 2)

    def _sample(self, shape):
        u = jax.random.uniform(_key(), _shape(shape, self._batch_shape))
        return jnp.floor(jnp.log1p(-u) / jnp.log1p(-self.probs))

    def _log_prob(self, v):
        return v * jnp.log1p(-self.probs) + jnp.log(self.probs)


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return Tensor(self.loc + self.scale * 0.5772156649015329)

    @property
    def variance(self):
        return Tensor((math.pi ** 2 / 6) * self.scale ** 2)

    def _sample(self, shape):
        g = jax.random.gumbel(_key(), _shape(shape, self._batch_shape))
        return self.loc + self.scale * g

    def _log_prob(self, v):
        z = (v - self.loc) / self.scale
        return -(z + jnp.exp(-z)) - jnp.log(self.scale)

    def _entropy(self):
        return jnp.broadcast_to(jnp.log(self.scale) + 1.5772156649015329,
                                self._batch_shape)


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _arr(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return Tensor(self.rate)

    @property
    def variance(self):
        return Tensor(self.rate)

    def _sample(self, shape):
        return jax.random.poisson(
            _key(), self.rate,
            _shape(shape, self._batch_shape)).astype(jnp.float32)

    def _log_prob(self, v):
        return v * jnp.log(self.rate) - self.rate - \
            jsp_special.gammaln(v + 1.0)


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = _arr(df)
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(
            self.df.shape, self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.where(self.df > 1, self.loc, jnp.nan))

    def _sample(self, shape):
        t = jax.random.t(_key(), self.df, _shape(shape, self._batch_shape))
        return self.loc + self.scale * t

    def _log_prob(self, v):
        d = self.df
        z = (v - self.loc) / self.scale
        return (jsp_special.gammaln((d + 1) / 2)
                - jsp_special.gammaln(d / 2)
                - 0.5 * jnp.log(d * math.pi) - jnp.log(self.scale)
                - (d + 1) / 2 * jnp.log1p(z * z / d))


class Binomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs = _arr(probs)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs)

    @property
    def variance(self):
        return Tensor(self.total_count * self.probs * (1 - self.probs))

    def _sample(self, shape):
        draws = jax.random.bernoulli(
            _key(), self.probs,
            (self.total_count,) + _shape(shape, self._batch_shape))
        return draws.astype(jnp.float32).sum(0)

    def _log_prob(self, v):
        n = self.total_count
        return (jsp_special.gammaln(n + 1.0)
                - jsp_special.gammaln(v + 1.0)
                - jsp_special.gammaln(n - v + 1.0)
                + v * jnp.log(self.probs)
                + (n - v) * jnp.log1p(-self.probs))
