"""KL divergence registry (≙ python/paddle/distribution/kl.py)."""

from __future__ import annotations

import jax.numpy as jnp
from jax.scipy import special as jsp_special

from ..core.tensor import Tensor
from . import distribution as D

_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return deco


def kl_divergence(p, q) -> Tensor:
    for (pc, qc), fn in _KL_REGISTRY.items():
        if isinstance(p, pc) and isinstance(q, qc):
            return Tensor(fn(p, q))
    raise NotImplementedError(
        f"no KL registered for ({type(p).__name__}, {type(q).__name__})")


@register_kl(D.Normal, D.Normal)
def _kl_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))


@register_kl(D.Uniform, D.Uniform)
def _kl_uniform(p, q):
    result = jnp.log((q.high - q.low) / (p.high - p.low))
    return jnp.where((q.low <= p.low) & (p.high <= q.high), result, jnp.inf)


@register_kl(D.Categorical, D.Categorical)
def _kl_categorical(p, q):
    return jnp.sum(p.probs * (p.logits - q.logits), axis=-1)


@register_kl(D.Bernoulli, D.Bernoulli)
def _kl_bernoulli(p, q):
    a = p.probs * (jnp.log(p.probs) - jnp.log(q.probs))
    b = (1 - p.probs) * (jnp.log1p(-p.probs) - jnp.log1p(-q.probs))
    return a + b


@register_kl(D.Beta, D.Beta)
def _kl_beta(p, q):
    sum_p = p.alpha + p.beta
    lbeta_p = (jsp_special.gammaln(p.alpha) + jsp_special.gammaln(p.beta)
               - jsp_special.gammaln(sum_p))
    lbeta_q = (jsp_special.gammaln(q.alpha) + jsp_special.gammaln(q.beta)
               - jsp_special.gammaln(q.alpha + q.beta))
    return (lbeta_q - lbeta_p
            + (p.alpha - q.alpha) * jsp_special.digamma(p.alpha)
            + (p.beta - q.beta) * jsp_special.digamma(p.beta)
            + (q.alpha - p.alpha + q.beta - p.beta)
            * jsp_special.digamma(sum_p))


@register_kl(D.Exponential, D.Exponential)
def _kl_exponential(p, q):
    ratio = q.rate / p.rate
    return jnp.log(1 / ratio) + ratio - 1


@register_kl(D.Gamma, D.Gamma)
def _kl_gamma(p, q):
    return ((p.concentration - q.concentration)
            * jsp_special.digamma(p.concentration)
            - jsp_special.gammaln(p.concentration)
            + jsp_special.gammaln(q.concentration)
            + q.concentration * (jnp.log(p.rate) - jnp.log(q.rate))
            + p.concentration * (q.rate / p.rate - 1))


@register_kl(D.Dirichlet, D.Dirichlet)
def _kl_dirichlet(p, q):
    a0 = jnp.sum(p.concentration, -1)
    return (jsp_special.gammaln(a0)
            - jnp.sum(jsp_special.gammaln(p.concentration), -1)
            - jsp_special.gammaln(jnp.sum(q.concentration, -1))
            + jnp.sum(jsp_special.gammaln(q.concentration), -1)
            + jnp.sum((p.concentration - q.concentration)
                      * (jsp_special.digamma(p.concentration)
                         - jsp_special.digamma(a0)[..., None]), -1))


@register_kl(D.Laplace, D.Laplace)
def _kl_laplace(p, q):
    scale_ratio = p.scale / q.scale
    loc_abs = jnp.abs(p.loc - q.loc) / q.scale
    return (-jnp.log(scale_ratio) + scale_ratio *
            jnp.exp(-loc_abs / scale_ratio) + loc_abs - 1)
