"""DLPack zero-copy tensor interchange.

Reference parity: ``python/paddle/utils/dlpack.py`` (to_dlpack/from_dlpack
over ``paddle/fluid/framework/dlpack_tensor.cc``).  Here the exchange is
the DLPack protocol on the underlying jax.Array — zero-copy on CPU and
same-device on TPU where the consumer supports it.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Tensor -> DLPack-protocol object (implements ``__dlpack__`` /
    ``__dlpack_device__``; DLPack 1.0 exchanges protocol objects rather
    than raw capsules — torch/numpy/jax ``from_dlpack`` all accept it)."""
    if not isinstance(x, Tensor):
        raise TypeError(f"to_dlpack expects a Tensor, got {type(x).__name__}")
    return x._value


def _is_capsule(obj):
    return type(obj).__name__ == "PyCapsule"


def from_dlpack(ext) -> Tensor:
    """Any object with ``__dlpack__`` -> Tensor (zero-copy where the
    producer allows it)."""
    if _is_capsule(ext):
        raise TypeError(
            "from_dlpack expects an object implementing the DLPack "
            "protocol (__dlpack__), not a raw capsule; pass the producing "
            "tensor/array itself")
    arr = jnp.from_dlpack(ext)
    return Tensor(arr)
