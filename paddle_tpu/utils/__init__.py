"""paddle_tpu.utils (analogue of ``python/paddle/utils``: dlpack interop,
cpp_extension custom-op build/load, run_check environment check)."""

from . import dlpack  # noqa: F401
from . import cpp_extension  # noqa: F401
from .install_check import run_check  # noqa: F401

__all__ = ["dlpack", "cpp_extension", "run_check"]
