"""MXU-FLOPs accounting from lowered jaxprs.

Counts the 2*MAC FLOPs of every ``dot_general`` and
``conv_general_dilated`` in a traced function, recursing through
pjit/remat/custom-vjp wrappers and multiplying ``scan`` bodies by their
trip count.  This is the honest-FLOPs source for conv-model MFU in
``bench.py`` and the compute term of the auto-parallel cost model
(reference analogue: the per-op flops registry behind
``python/paddle/distributed/auto_parallel/static/cost/estimate_cost.py``
and the profiler flops columns of ``tools/check_op_benchmark_result.py``).
"""

from __future__ import annotations

import math

import jax

__all__ = ["count_matmul_flops", "jaxpr_matmul_flops"]


def _dot_general_flops(eqn):
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs, rhs = (v.aval.shape for v in eqn.invars[:2])
    batch = math.prod(lhs[d] for d in lb) if lb else 1
    k = math.prod(lhs[d] for d in lc) if lc else 1
    m = math.prod(d for i, d in enumerate(lhs) if i not in set(lc) | set(lb))
    n = math.prod(d for i, d in enumerate(rhs) if i not in set(rc) | set(rb))
    return 2 * batch * m * n * k


def _conv_flops(eqn):
    dn = eqn.params["dimension_numbers"]
    groups = eqn.params.get("feature_group_count", 1)
    rhs = eqn.invars[1].aval.shape
    out = eqn.outvars[0].aval.shape
    # rhs_spec = (out_c dim, in_c/groups dim, *spatial)
    cin_per_group = rhs[dn.rhs_spec[1]]
    kernel = math.prod(rhs[d] for d in dn.rhs_spec[2:])
    # out elems already include out_c, batch, spatial; batch_group_count
    # rescales out_c, leaving the product correct
    return 2 * math.prod(out) * cin_per_group * kernel


def jaxpr_matmul_flops(jaxpr) -> int:
    """Total 2*MAC FLOPs of dot_general/conv ops in ``jaxpr`` (a Jaxpr or
    ClosedJaxpr).  ``while`` bodies count once (trip count is dynamic);
    ``cond`` counts its most expensive branch."""
    if hasattr(jaxpr, "jaxpr"):  # ClosedJaxpr
        jaxpr = jaxpr.jaxpr
    total = 0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            total += _dot_general_flops(eqn)
        elif name == "conv_general_dilated":
            total += _conv_flops(eqn)
        elif name == "scan":
            total += eqn.params["length"] * \
                jaxpr_matmul_flops(eqn.params["jaxpr"])
        elif name == "while":
            total += jaxpr_matmul_flops(eqn.params["body_jaxpr"])
        elif name == "cond":
            total += max((jaxpr_matmul_flops(b)
                          for b in eqn.params["branches"]), default=0)
        else:
            for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
                sub = eqn.params.get(key)
                if sub is not None:
                    total += jaxpr_matmul_flops(sub)
                    break
    return total


def count_matmul_flops(fn, *args, **kwargs) -> int:
    """Trace ``fn`` (positional ``args`` may be Tensors or arrays) and
    return its total matmul/conv FLOPs."""
    from ..core.tensor import Tensor

    vals = [a._value if isinstance(a, Tensor) else a for a in args]
    jaxpr = jax.make_jaxpr(fn, **kwargs)(*vals)
    return jaxpr_matmul_flops(jaxpr)
