"""Environment self-check (reference: ``python/paddle/utils/install_check.py``
``run_check()``: verifies the install by running a tiny training step and
reporting the devices found)."""

from __future__ import annotations

import numpy as np

__all__ = ["run_check"]


def run_check():
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer

    devices = jax.devices()
    print(f"Running verify on {len(devices)} {devices[0].platform} "
          "device(s).")
    model = nn.Linear(4, 2)
    opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    y = paddle.to_tensor(np.zeros((2,), np.int64))
    loss = nn.functional.cross_entropy(model(x), y)
    loss.backward()
    opt.step()
    if not np.isfinite(float(loss)):
        raise RuntimeError("paddle_tpu self-check produced a non-finite "
                           "loss; the installation is broken")
    print("paddle_tpu is installed successfully!")
