"""Custom C++ op build-and-load.

Capability analogue of ``python/paddle/utils/cpp_extension/cpp_extension.py``
(``load()``:799 JIT build + ``setup()``:79) and the runtime registration in
``paddle/fluid/framework/custom_operator.cc:958``.

TPU-native design: a custom C++ op is a *host* op — compiled with g++ into
a shared library, called through ctypes, and wrapped in
``jax.pure_callback`` so it composes with jit/vmap tracing exactly like a
phi CPU kernel composes with the CUDA graph in the reference (XLA treats
it as a host custom-call).  Device-side custom kernels are written in
Pallas instead (see paddle_tpu.ops.pallas) — the reference's .cu path has
no place on TPU.

C ABI contract (one function per op):

    extern "C" void <name>(const float* x, float* out, int64_t n);

elementwise over ``n`` floats; richer signatures can be registered by
passing ``arity=2`` for binary ops:

    extern "C" void <name>(const float* x, const float* y, float* out,
                           int64_t n);

Ops are registered into ``paddle_tpu._C_ops`` by name; an optional
``vjp`` (another loaded op name or python fn) makes them differentiable.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Optional, Sequence

import numpy as np

__all__ = ["load", "CppExtension", "setup", "get_build_directory",
           "register_python_op"]


def get_build_directory():
    d = os.environ.get("PADDLE_TPU_EXTENSION_DIR",
                       os.path.join(tempfile.gettempdir(),
                                    "paddle_tpu_extensions"))
    os.makedirs(d, exist_ok=True)
    return d


def _compile(name: str, sources: Sequence[str],
             extra_cxx_flags: Sequence[str] = (),
             verbose: bool = False) -> str:
    """g++ -shared -fPIC sources -> <build_dir>/<name>_<hash>.so
    (recompiled only when sources change — the reference's version-hash
    cache in extension_utils)."""
    build_dir = get_build_directory()
    h = hashlib.sha256()
    for s in sources:
        with open(s, "rb") as f:
            h.update(f.read())
    h.update(" ".join(extra_cxx_flags).encode())
    so_path = os.path.join(build_dir, f"{name}_{h.hexdigest()[:12]}.so")
    if not os.path.exists(so_path):
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
               *extra_cxx_flags, *sources, "-o", so_path]
        if verbose:
            print("compiling:", " ".join(cmd))
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"custom op compilation failed:\n{proc.stderr}")
    return so_path


class _LoadedModule:
    """Holds python wrappers for each exported op function."""

    def __init__(self, name):
        self.name = name
        self._fns = {}

    def __getattr__(self, item):
        try:
            return self.__dict__["_fns"][item]
        except KeyError:
            raise AttributeError(
                f"custom module {self.name!r} has no op {item!r}; "
                f"available: {list(self.__dict__['_fns'])}")


def _wrap_host_op(op_name: str, cfn, arity: int, vjp=None):
    """ctypes fn -> framework op via jax.pure_callback (works eagerly,
    under jit, and on TPU as a host custom-call)."""
    import jax
    import jax.numpy as jnp

    from ..core.dispatch import dispatch
    from ..core.tensor import Tensor

    def host_compute(*arrays):
        arrs = [np.ascontiguousarray(np.asarray(a, np.float32))
                for a in arrays]
        out = np.empty_like(arrs[0])
        n = ctypes.c_int64(arrs[0].size)
        ptrs = [a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
                for a in arrs]
        cfn(*ptrs, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), n)
        return out

    def _callback(*a):
        shape = jax.ShapeDtypeStruct(a[0].shape, jnp.float32)
        return jax.pure_callback(host_compute, shape, *a,
                                 vmap_method="sequential")

    if vjp is not None:
        # differentiable via custom vjp: vjp(grad_out, *inputs) -> grads.
        # The callback itself must only ever be traced through the custom
        # rule (pure_callback has no JVP).
        diff_f = jax.custom_vjp(_callback)

        def fwd(*a):
            return _callback(*a), a

        def bwd(res, g):
            grads = vjp(g, *res)
            return tuple(grads) if isinstance(grads, (tuple, list)) \
                else (grads,)

        diff_f.defvjp(fwd, bwd)
        impl = diff_f
    else:
        impl = _callback

    def py_op(*tensors):
        if len(tensors) != arity:
            raise TypeError(
                f"custom op {op_name!r} expects {arity} inputs, got "
                f"{len(tensors)}")
        nondiff = None if vjp is not None else [True] * arity
        return dispatch(op_name, impl, tensors, nondiff_mask=nondiff)

    py_op.__name__ = op_name
    return py_op


def load(name: str, sources: Sequence[str], functions=None,
         extra_cxx_flags: Sequence[str] = (), arities=None, vjps=None,
         verbose: bool = False) -> _LoadedModule:
    """Compile + load custom C++ host ops and register them.

    functions: exported symbol names (default: [name]).
    arities: per-function input count (default 1).
    vjps: per-function python vjp callable or None.
    """
    functions = functions or [name]
    arities = arities or {}
    vjps = vjps or {}
    so_path = _compile(name, sources, extra_cxx_flags, verbose)
    lib = ctypes.CDLL(so_path)
    module = _LoadedModule(name)
    from .. import _C_ops
    for fn_name in functions:
        if hasattr(_C_ops, fn_name):
            raise ValueError(
                f"custom op name {fn_name!r} collides with an existing "
                "_C_ops entry; rename the exported symbol (builtin ops "
                "cannot be shadowed by custom host ops)")
        cfn = getattr(lib, fn_name)
        arity = arities.get(fn_name, 1)
        cfn.restype = None
        cfn.argtypes = ([ctypes.POINTER(ctypes.c_float)] * (arity + 1)
                        + [ctypes.c_int64])
        wrapper = _wrap_host_op(fn_name, cfn, arity, vjps.get(fn_name))
        module._fns[fn_name] = wrapper
        setattr(_C_ops, fn_name, wrapper)  # runtime registration
    return module


def register_python_op(name: str, fn, vjp=None):
    """Register a pure-python/jnp custom op into paddle_tpu._C_ops (the
    analogue of a python-implemented custom op; differentiable if vjp
    given or if fn is jnp-traceable)."""
    from ..core.dispatch import dispatch
    from .. import _C_ops

    if hasattr(_C_ops, name):
        raise ValueError(
            f"custom op name {name!r} collides with an existing _C_ops "
            "entry; pick a different name")

    def py_op(*tensors):
        return dispatch(name, fn, tensors)

    py_op.__name__ = name
    setattr(_C_ops, name, py_op)
    return py_op


class CppExtension:
    """setup()-style extension description (reference CppExtension)."""

    def __init__(self, sources, name=None, extra_compile_args=()):
        self.sources = list(sources)
        self.name = name
        self.extra_compile_args = list(extra_compile_args)


def setup(name: str, ext_modules, **kwargs):
    """Eager build of extensions (the reference's setuptools path builds a
    wheel; here we build+load in place and return the loaded modules)."""
    exts = ext_modules if isinstance(ext_modules, (list, tuple)) \
        else [ext_modules]
    return [load(e.name or name, e.sources,
                 extra_cxx_flags=e.extra_compile_args, **kwargs)
            for e in exts]
