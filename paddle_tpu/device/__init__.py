"""``paddle_tpu.device`` — device management package (analogue of
``python/paddle/device/__init__.py``: set_device:244, get_device:271,
Stream/Event wrappers :410, plus the ``cuda`` submodule — here ``tpu``).
Implementation lives in ``paddle_tpu.core.device``; this package gives the
reference's import surface."""

from ..core.device import (  # noqa: F401
    Place, CPUPlace, TPUPlace, get_all_device_type, device_count,
    set_device, get_device, current_place, is_compiled_with_cuda,
    is_compiled_with_tpu, synchronize, Stream, Event, current_stream,
    stream_guard, memory_stats, max_memory_allocated, memory_allocated,
    empty_cache)

from . import tpu  # noqa: F401
from . import cuda  # noqa: F401

__all__ = [
    "Place", "CPUPlace", "TPUPlace", "get_all_device_type", "device_count",
    "set_device", "get_device", "current_place", "is_compiled_with_cuda",
    "is_compiled_with_tpu", "synchronize", "Stream", "Event",
    "current_stream", "stream_guard", "memory_stats",
    "max_memory_allocated", "memory_allocated", "empty_cache",
    "tpu", "cuda",
]


def get_available_device():
    return [f"{t}:{i}" for t in get_all_device_type()
            for i in range(device_count(t))]


def get_available_custom_device():
    # PJRT plugins appear as regular jax backends; nothing extra to surface.
    return [d for d in get_available_device()
            if not d.startswith(("cpu", "tpu", "gpu"))]
