"""``paddle_tpu.device.cuda`` — API-parity shim for code written against
``paddle.device.cuda``.  This build has no CUDA (is_compiled_with_cuda() is
False); the calls map onto the same backend-agnostic facade as
``device.tpu`` so device-generic user code keeps working."""

from ..tpu import (  # noqa: F401
    Stream, Event, current_stream, stream_guard, synchronize,
    memory_stats, max_memory_allocated, memory_allocated,
    max_memory_reserved, memory_reserved, empty_cache)


def device_count() -> int:
    """0: this build has no CUDA.  Keeps the reference GPU-detection idiom
    (``if device_count() > 0``) truthful on CUDA-less builds."""
    return 0

__all__ = [
    "Stream", "Event", "current_stream", "stream_guard", "synchronize",
    "device_count", "memory_stats", "max_memory_allocated",
    "memory_allocated", "max_memory_reserved", "memory_reserved",
    "empty_cache",
]
