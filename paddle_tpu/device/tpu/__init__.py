"""``paddle_tpu.device.tpu`` — per-accelerator utilities (analogue of
``python/paddle/device/cuda/__init__.py``: Stream, Event, memory stats,
empty_cache, synchronize — for the TPU backend)."""

from ...core.device import (  # noqa: F401
    Stream, Event, current_stream, stream_guard, synchronize,
    memory_stats, max_memory_allocated, memory_allocated, empty_cache,
    device_count as _device_count,
)

__all__ = [
    "Stream", "Event", "current_stream", "stream_guard", "synchronize",
    "device_count", "memory_stats", "max_memory_allocated",
    "memory_allocated", "max_memory_reserved", "memory_reserved",
    "empty_cache",
]


def device_count() -> int:
    return _device_count("tpu") or _device_count()


def max_memory_reserved(device=None) -> int:
    s = memory_stats(device)
    return int(s.get("peak_pool_bytes", s.get("peak_bytes_in_use", 0)))


def memory_reserved(device=None) -> int:
    s = memory_stats(device)
    return int(s.get("pool_bytes", s.get("bytes_in_use", 0)))
