"""Quantization (QAT + PTQ) — capability analogue of ``paddle.quantization``
(reference: ``python/paddle/quantization/{config.py,qat.py,ptq.py}``,
imperative QAT in ``python/paddle/quantization/imperative/qat.py`` and the
static PTQ/QAT tooling under ``python/paddle/static/quantization``).

TPU-native design: fake-quantization is expressed as quantize-dequantize
(QDQ) with a straight-through-estimator gradient — ``x + stop_gradient(
dq(q(x)) - x)`` — which XLA folds into the surrounding matmul; the
converted inference model carries int8 weights with per-tensor or
per-channel scales and computes in bf16/fp32 after dequant (int8 MXU
matmul is a kernel-level optimization the Pallas pack can add without
changing this surface).
"""

from .config import QuantConfig
from .observers import (AbsmaxObserver, MovingAverageAbsmaxObserver,
                        PerChannelAbsmaxObserver, BaseObserver,
                        absmax_to_scales, quantize_channelwise)
from .quanters import (BaseQuanter, FakeQuanterWithAbsMaxObserver,
                       FakeQuanterChannelWiseAbsMaxObserver,
                       quantize_tensor, dequantize_tensor, fake_quant)
from .qat import QAT
from .ptq import PTQ, fuse_act_into_quant_linear, weight_only_quantize

__all__ = [
    "QuantConfig", "QAT", "PTQ", "weight_only_quantize",
    "fuse_act_into_quant_linear",
    "BaseObserver", "AbsmaxObserver", "MovingAverageAbsmaxObserver",
    "PerChannelAbsmaxObserver", "absmax_to_scales", "quantize_channelwise",
    "BaseQuanter", "FakeQuanterWithAbsMaxObserver",
    "FakeQuanterChannelWiseAbsMaxObserver",
    "quantize_tensor", "dequantize_tensor", "fake_quant",
]
