"""QuantConfig — declares which layers get which quanters/observers.

Reference parity: ``paddle.quantization.QuantConfig``
(python/paddle/quantization/config.py): global activation/weight factories
plus per-layer and per-type overrides.
"""

from __future__ import annotations

from typing import Optional

from ..nn import Layer


class _FactorySpec:
    """Holds a quanter/observer class partially applied with kwargs."""

    def __init__(self, cls=None, **kwargs):
        self.cls = cls
        self.kwargs = kwargs

    def instance(self):
        return None if self.cls is None else self.cls(**self.kwargs)


def quanter_factory(cls, **kwargs):
    return _FactorySpec(cls, **kwargs)


class _Unset:
    """Sentinel: distinguishes "not overridden" from an explicit None
    (which exempts the layer from the global quanter)."""


_UNSET = _Unset()


class QuantConfig:
    def __init__(self, activation=None, weight=None):
        self._global_act = self._as_spec(activation)
        self._global_weight = self._as_spec(weight)
        self._layer_overrides = []   # (layer_instance, act, weight)
        self._type_overrides = []    # (layer_type, act, weight)

    @staticmethod
    def _as_spec(q):
        if q is None or q is _UNSET or isinstance(q, _FactorySpec):
            return q
        if isinstance(q, type):
            return _FactorySpec(q)
        raise TypeError(f"expected a quanter class or factory, got {q!r}")

    def add_layer_config(self, layer, activation=_UNSET, weight=_UNSET):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for l in layers:
            self._layer_overrides.append(
                (l, self._as_spec(activation), self._as_spec(weight)))

    def add_type_config(self, layer_type, activation=_UNSET, weight=_UNSET):
        types = layer_type if isinstance(layer_type, (list, tuple)) \
            else [layer_type]
        for t in types:
            self._type_overrides.append(
                (t, self._as_spec(activation), self._as_spec(weight)))

    def _specs_for(self, layer: Layer):
        for inst, act, w in self._layer_overrides:
            if inst is layer:
                return (self._global_act if act is _UNSET else act,
                        self._global_weight if w is _UNSET else w)
        for t, act, w in self._type_overrides:
            if isinstance(layer, t):
                return (self._global_act if act is _UNSET else act,
                        self._global_weight if w is _UNSET else w)
        return self._global_act, self._global_weight

    def activation_quanter_for(self, layer) -> Optional[Layer]:
        act, _ = self._specs_for(layer)
        return act.instance() if act else None

    def weight_quanter_for(self, layer) -> Optional[Layer]:
        _, w = self._specs_for(layer)
        return w.instance() if w else None
