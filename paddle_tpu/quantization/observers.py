"""PTQ observers — collect activation statistics during calibration.

Reference parity: ``paddle.quantization.observers.AbsmaxObserver`` plus the
moving-average and per-channel variants used by the static PTQ tooling
(python/paddle/static/quantization/quanter.py scale strategies).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn import Layer


def absmax_to_scales(absmax, bit_length: int = 8):
    """THE quant rule: absmax statistics -> per-channel (or scalar)
    quantization scales.  ``scale = max(absmax, 1e-9) / qmax`` with
    ``qmax = 2**(bits-1) - 1`` — the epsilon floor lands on the absmax
    BEFORE the divide so composing with an observer's already-floored
    ``scales()`` output is idempotent (observer path and any loader path
    agree bit-exactly).  Every weight-quantization site (QAT freeze, PTQ
    weight-only, the serving engine's weight_dtype loader) must call
    this, not re-derive it."""
    qmax = float(2 ** (bit_length - 1) - 1)
    return jnp.maximum(jnp.asarray(absmax, jnp.float32), 1e-9) / qmax


def quantize_channelwise(w, scales, bit_length: int = 8,
                         quant_axis: int = -1):
    """Codes for ``w`` against per-channel ``scales`` along
    ``quant_axis``: ``clip(round(w / scale), -qmax, qmax)`` as int8
    (int4 codes also ride in an int8 container, range [-7, 7])."""
    qmax = float(2 ** (bit_length - 1) - 1)
    w = jnp.asarray(w, jnp.float32)
    axis = quant_axis % w.ndim
    shape = [1] * w.ndim
    shape[axis] = -1
    s = jnp.asarray(scales, jnp.float32).reshape(shape)
    return jnp.clip(jnp.round(w / s), -qmax, qmax).astype(jnp.int8)


class BaseObserver(Layer):
    """Observers are identity layers that record statistics; ``scales()``
    yields the calibrated quantization scale (absmax)."""

    def __init__(self, bit_length: int = 8):
        super().__init__()
        self._bits = bit_length

    def bit_length(self):
        return self._bits

    def quant_axis(self):
        return None

    def scales(self):
        raise NotImplementedError

    def forward(self, x):
        self.observe(x)
        return x

    def observe(self, x):
        raise NotImplementedError


class AbsmaxObserver(BaseObserver):
    """Global absmax over everything seen during calibration."""

    def __init__(self, bit_length: int = 8, **kwargs):
        super().__init__(bit_length)
        self._absmax = 0.0

    def observe(self, x):
        cur = float(jnp.max(jnp.abs(jnp.asarray(x._value, jnp.float32))))
        self._absmax = max(self._absmax, cur)

    def scales(self):
        return Tensor(jnp.asarray(max(self._absmax, 1e-9), jnp.float32))


class MovingAverageAbsmaxObserver(BaseObserver):
    def __init__(self, moving_rate: float = 0.9, bit_length: int = 8,
                 **kwargs):
        super().__init__(bit_length)
        self._moving_rate = moving_rate
        self._absmax = None

    def observe(self, x):
        cur = float(jnp.max(jnp.abs(jnp.asarray(x._value, jnp.float32))))
        if self._absmax is None:
            self._absmax = cur
        else:
            self._absmax = (self._moving_rate * self._absmax +
                            (1 - self._moving_rate) * cur)

    def scales(self):
        return Tensor(jnp.asarray(max(self._absmax or 0.0, 1e-9),
                                  jnp.float32))


class PerChannelAbsmaxObserver(BaseObserver):
    def __init__(self, quant_axis: int = -1, bit_length: int = 8, **kwargs):
        super().__init__(bit_length)
        self._axis = quant_axis
        self._absmax = None

    def quant_axis(self):
        return self._axis

    def observe(self, x):
        axis = self._axis % x.ndim
        reduce_axes = tuple(i for i in range(x.ndim) if i != axis)
        cur = jnp.max(jnp.abs(jnp.asarray(x._value, jnp.float32)),
                      axis=reduce_axes)
        self._absmax = cur if self._absmax is None \
            else jnp.maximum(self._absmax, cur)

    def scales(self):
        return Tensor(jnp.maximum(self._absmax, 1e-9))
