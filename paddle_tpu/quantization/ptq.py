"""PTQ — post-training quantization driver.

Reference parity: ``paddle.quantization.PTQ``
(python/paddle/quantization/ptq.py): ``quantize(model)`` inserts observers
in front of quantizable layers; the user runs calibration batches; then
``convert(model)`` freezes observed scales into the int8 inference model.
"""

from __future__ import annotations

from ..nn import Layer, Linear
from ..nn.layer.conv import Conv2D
from ..nn.quant.quant_layers import QuantedLinear, QuantedConv2D
from .config import QuantConfig
from .qat import QAT, _freeze
from .observers import BaseObserver, PerChannelAbsmaxObserver


class _ObservedLayer(Layer):
    """Float layer with an input observer attached (calibration phase)."""

    def __init__(self, layer, observer):
        super().__init__()
        self.inner = layer
        self.observer = observer

    def forward(self, *args, **kwargs):
        if self.observer is not None and args:
            self.observer.observe(args[0])
        return self.inner(*args, **kwargs)


def weight_only_quantize(model: Layer, inplace: bool = True,
                         skip=None) -> Layer:
    """Weight-only int8 conversion for serving: every ``nn.Linear``
    becomes a ``QuantizedLinearInfer`` (int8 weights + per-out-channel
    scales, activations stay float).  No calibration pass — decode
    serving is weight-streaming bound, and halving weight bytes is the
    whole win (reference analogue: the weight-only int8 mode of
    ``fused_multi_transformer_int8_op.cu`` / TRT weight-only PTQ).

    ``skip(qualified_name, layer) -> bool`` excludes layers (e.g. the
    lm_head, commonly kept float for accuracy).
    """
    if not inplace:
        import copy
        # Don't deepcopy compiled generate() executables (and the weight
        # lists their closures pin) just to discard them below.
        saved_cache = model.__dict__.pop("_generate_exe_cache", None)
        original = model
        try:
            model = copy.deepcopy(model)
        finally:
            if saved_cache is not None:
                original.__dict__["_generate_exe_cache"] = saved_cache
    converted = 0

    def rec(layer: Layer, prefix: str):
        nonlocal converted
        for name, sub in list(layer._sub_layers.items()):
            qual = f"{prefix}.{name}" if prefix else name
            if isinstance(sub, Linear):
                if skip is not None and skip(qual, sub):
                    continue
                layer._sub_layers[name] = _freeze(
                    QuantedLinear(sub, None, None))
                converted += 1
            else:
                rec(sub, qual)

    rec(model, "")
    if converted == 0:
        raise ValueError(
            "weight_only_quantize converted no layers — the model has no "
            "nn.Linear sublayers (tensor-parallel Column/RowParallelLinear "
            "are not yet supported for int8 serving; quantize the "
            "unsharded model)")
    # Structural mutation invalidates any compiled generate() programs
    # (their closures captured the pre-quantization param/buffer lists).
    if getattr(model, "_generate_exe_cache", None):
        model._generate_exe_cache.clear()
    return model


_FUSABLE_ACTS = {"GELU": "gelu", "ReLU": "relu", "Silu": "silu",
                 "SiLU": "silu"}


def fuse_act_into_quant_linear(model: Layer) -> int:
    """Fold ``nn.Sequential``-adjacent activation layers (GELU/ReLU/Silu)
    into the preceding ``QuantizedLinearInfer``'s kernel epilogue and
    replace them with Identity.  The conv_bn-fuse/TRT-epilogue role
    (reference ``conv_bn_fuse_pass.cc`` tradition): a Pallas custom call
    is an XLA fusion barrier, so WITHOUT this the dequant+bias+act
    materialize between kernels.  Returns the number of pairs fused.
    The fused GELU uses the tanh approximation (Mosaic has no erf):
    <= ~3e-3 absolute deviation from the exact form, under the int8
    quantization error; ``approximate=True`` GELU layers fuse to the
    same formula."""
    from ..nn.layer.common import Identity
    from ..nn.quant.quant_layers import QuantizedLinearInfer
    fused = 0

    def rec(layer: Layer):
        nonlocal fused
        from ..nn.layer.container import Sequential
        if isinstance(layer, Sequential):
            items = list(layer._sub_layers.items())
            for (n1, a), (n2, b) in zip(items, items[1:]):
                act = _FUSABLE_ACTS.get(type(b).__name__)
                if act is None or not isinstance(a, QuantizedLinearInfer):
                    continue
                if a._fused_act is not None:
                    continue
                a._fused_act = act
                layer._sub_layers[n2] = Identity()
                fused += 1
        for sub in layer._sub_layers.values():
            rec(sub)

    rec(model)
    return fused


class PTQ:
    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model: Layer, inplace: bool = True) -> Layer:
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        self._insert_rec(model)
        return model

    def _insert_rec(self, layer: Layer):
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, (Linear, Conv2D)):
                obs = self._config.activation_quanter_for(sub)
                if obs is not None and not isinstance(obs, BaseObserver):
                    raise TypeError(
                        "PTQ activation config must be an observer class, "
                        f"got {type(obs).__name__}")
                if obs is not None:
                    layer._sub_layers[name] = _ObservedLayer(sub, obs)
            else:
                self._insert_rec(sub)

    def convert(self, model: Layer, inplace: bool = True) -> Layer:
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        self._convert_rec(model)
        return model

    def _convert_rec(self, layer: Layer):
        from .quanters import FakeQuanterChannelWiseAbsMaxObserver
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, _ObservedLayer):
                inner = sub.inner
                act_scale = sub.observer.scales() if sub.observer else None
                # honor the configured weight observer's bit width (the
                # weight scales themselves are recomputed per-channel from
                # the frozen weights)
                wspec = self._config.weight_quanter_for(inner)
                bits = wspec.bit_length() if wspec is not None else 8
                wq = FakeQuanterChannelWiseAbsMaxObserver(bit_length=bits)
                wrapper_cls = QuantedLinear if isinstance(inner, Linear) \
                    else QuantedConv2D
                q = wrapper_cls(inner, None, wq)
                frozen = _freeze(q)
                frozen._act_scale = act_scale
                layer._sub_layers[name] = frozen
            else:
                self._convert_rec(sub)
