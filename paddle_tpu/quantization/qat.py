"""QAT — quantization-aware training driver.

Reference parity: ``paddle.quantization.QAT``
(python/paddle/quantization/qat.py): ``quantize(model)`` swaps supported
layers for fake-quantized wrappers in place of training; ``convert(model)``
freezes scales and emits the int8-weight inference model.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..nn import Layer, Linear
from ..nn.layer.conv import Conv2D
from ..nn.quant.quant_layers import (QuantedLinear, QuantedConv2D,
                                     QuantizedLinearInfer,
                                     QuantizedConv2DInfer)
from .config import QuantConfig
from .observers import absmax_to_scales, quantize_channelwise
from .quanters import FakeQuanterChannelWiseAbsMaxObserver


class QAT:
    def __init__(self, config: QuantConfig):
        self._config = config

    def _wrap(self, layer):
        act = self._config.activation_quanter_for(layer)
        weight = self._config.weight_quanter_for(layer)
        if act is None and weight is None:
            return None
        if isinstance(layer, Linear):
            if isinstance(weight, FakeQuanterChannelWiseAbsMaxObserver):
                weight._axis = -1  # out-features axis of [in, out]
            return QuantedLinear(layer, act, weight)
        if isinstance(layer, Conv2D):
            if isinstance(weight, FakeQuanterChannelWiseAbsMaxObserver):
                weight._axis = 0   # out-channels axis of [out, in, kh, kw]
            return QuantedConv2D(layer, act, weight)
        return None

    def quantize(self, model: Layer, inplace: bool = True) -> Layer:
        """Replace every quantizable sublayer with its QAT wrapper."""
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        self._quantize_rec(model)
        return model

    def _quantize_rec(self, layer: Layer):
        for name, sub in list(layer._sub_layers.items()):
            wrapped = self._wrap(sub)
            if wrapped is not None:
                layer._sub_layers[name] = wrapped
            else:
                self._quantize_rec(sub)

    def convert(self, model: Layer, inplace: bool = True) -> Layer:
        """Freeze a trained QAT model into the int8 inference form."""
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        self._convert_rec(model)
        return model

    def _convert_rec(self, layer: Layer):
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, (QuantedLinear, QuantedConv2D)):
                layer._sub_layers[name] = _freeze(sub)
            else:
                self._convert_rec(sub)


def _freeze(qlayer):
    """Snapshot weight scales, quantize the weight to int8, and build the
    inference layer."""
    w = jnp.asarray(qlayer.weight._value, jnp.float32)
    bits = (qlayer.weight_quanter.bit_length()
            if qlayer.weight_quanter is not None else 8)
    act_scale = None
    if qlayer.activation_quanter is not None:
        act_scale = qlayer.activation_quanter.scales()

    if isinstance(qlayer, QuantedLinear):
        axis = 1  # [in, out] -> per-out-channel
        reduce_axes = (0,)
        scales = absmax_to_scales(jnp.max(jnp.abs(w), axis=reduce_axes),
                                  bits)
        qw = quantize_channelwise(w, scales, bits, quant_axis=axis)
        return QuantizedLinearInfer(
            qw, scales, qlayer.bias, qlayer._float_layer.in_features,
            qlayer._float_layer.out_features, act_scale, bits)

    axis = 0  # conv [out, in, kh, kw]
    reduce_axes = tuple(range(1, w.ndim))
    scales = absmax_to_scales(jnp.max(jnp.abs(w), axis=reduce_axes), bits)
    qw = quantize_channelwise(w, scales, bits, quant_axis=axis)
    conv_args = (qlayer._stride, qlayer._padding, qlayer._dilation,
                 qlayer._groups, qlayer._data_format)
    return QuantizedConv2DInfer(qw, scales, qlayer.bias, conv_args,
                                act_scale, bits)
