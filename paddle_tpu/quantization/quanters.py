"""Fake quanters (QDQ with straight-through gradients).

Reference parity: ``paddle.quantization.quanters.FakeQuanterWithAbsMaxObserver``
(python/paddle/quantization/quanters/abs_max.py) and the channel-wise
variant used for conv/linear weights.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..core.dispatch import dispatch
from ..core.tensor import Tensor
from ..nn import Layer


def _qrange(bits: int):
    qmax = float(2 ** (bits - 1) - 1)
    return -qmax, qmax


def quantize_tensor(x, scale, bits: int = 8, axis=None):
    """real -> int: round(x / scale) clipped to the signed range."""
    qmin, qmax = _qrange(bits)

    def impl(a, s):
        if axis is not None:
            shape = [1] * a.ndim
            shape[axis] = -1
            s = s.reshape(shape)
        q = jnp.clip(jnp.round(a / s), qmin, qmax)
        return q.astype(jnp.int8 if bits <= 8 else jnp.int32)

    return dispatch("quantize", impl, (x, scale), nondiff_mask=[True, True])


def dequantize_tensor(q, scale, axis=None):
    def impl(a, s):
        if axis is not None:
            shape = [1] * a.ndim
            shape[axis] = -1
            s = s.reshape(shape)
        return a.astype(jnp.float32) * s

    return dispatch("dequantize", impl, (q, scale), nondiff_mask=[True, True])


def fake_quant(x, scale, bits: int = 8, axis=None):
    """QDQ with straight-through estimator: gradient of round/clip is
    identity inside the representable range (STE), which is exactly
    ``x + stop_gradient(qdq(x) - x)``."""
    qmin, qmax = _qrange(bits)

    def impl(a, s):
        sf = jnp.maximum(jnp.asarray(s, jnp.float32), 1e-9)
        if axis is not None:
            shape = [1] * a.ndim
            shape[axis] = -1
            sf = sf.reshape(shape)
        qdq = jnp.clip(jnp.round(a / sf), qmin, qmax) * sf
        return a + lax.stop_gradient(qdq - a.astype(qdq.dtype)).astype(a.dtype)

    return dispatch("fake_quantize_dequantize", impl, (x, scale),
                    nondiff_mask=[False, True])


class BaseQuanter(Layer):
    """A quanter is a Layer inserted into the model; calling it fake-quants
    its input and (in training) updates its observer statistics."""

    def scales(self):
        raise NotImplementedError

    def quant_axis(self):
        return None

    def bit_length(self):
        return self._bits


class FakeQuanterWithAbsMaxObserver(BaseQuanter):
    """Per-tensor moving-average absmax fake quanter (activation quanter).

    Matches the reference quanter of the same name: in training mode the
    scale is the EMA of per-batch absmax; in eval mode the stored scale
    is used.
    """

    def __init__(self, moving_rate: float = 0.9, bit_length: int = 8,
                 **kwargs):
        super().__init__()
        self._moving_rate = moving_rate
        self._bits = bit_length
        self.register_buffer("scale", Tensor(jnp.ones((), jnp.float32)))
        self.register_buffer("_initialized",
                             Tensor(jnp.zeros((), jnp.bool_)))

    def scales(self):
        return self.scale

    def forward(self, x):
        if self.training:
            # pure-jnp EMA update so the quanter traces under jit/TrainStep
            # (buffers are threaded through the compiled step like
            # batch-norm running stats)
            cur = jnp.maximum(jnp.max(jnp.abs(
                jnp.asarray(x._value).astype(jnp.float32))), 1e-9)
            prev = jnp.asarray(self.scale._value, jnp.float32)
            new = jnp.where(self._initialized._value,
                            self._moving_rate * prev +
                            (1 - self._moving_rate) * cur,
                            cur)
            self.scale.set_value(new)
            self._initialized.set_value(jnp.ones((), jnp.bool_))
        # stored scale is the absmax (reference semantics); the QDQ step
        # size is absmax / qmax
        qmax = float(2 ** (self._bits - 1) - 1)
        step = Tensor(jnp.asarray(self.scale._value, jnp.float32) / qmax)
        return fake_quant(x, step, bits=self._bits)


class FakeQuanterChannelWiseAbsMaxObserver(BaseQuanter):
    """Per-channel absmax fake quanter (weight quanter): scale is computed
    from the current weight every call — weights change each step, and the
    convert step snapshots the final scales."""

    def __init__(self, quant_axis: int = -1, bit_length: int = 8, **kwargs):
        super().__init__()
        self._axis = quant_axis
        self._bits = bit_length
        self._last_scale = None

    def quant_axis(self):
        return self._axis

    def scales(self):
        return self._last_scale

    def forward(self, w):
        axis = self._axis % w.ndim
        reduce_axes = tuple(i for i in range(w.ndim) if i != axis)
        scale_arr = jnp.max(jnp.abs(jnp.asarray(w._value, jnp.float32)),
                            axis=reduce_axes)
        qmax = float(2 ** (self._bits - 1) - 1)
        scale_arr = jnp.maximum(scale_arr / qmax, 1e-9)
        self._last_scale = Tensor(scale_arr)
        return fake_quant(w, self._last_scale, bits=self._bits, axis=axis)
