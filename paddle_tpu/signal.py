"""paddle_tpu.signal — STFT/ISTFT (≙ python/paddle/signal.py)."""

from __future__ import annotations

import jax.numpy as jnp

from .core.dispatch import dispatch
from .core.tensor import Tensor

__all__ = ["stft", "istft", "frame", "overlap_add"]


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice overlapping frames along ``axis`` (last-axis framing)."""

    def impl(a):
        n = a.shape[axis]
        num = 1 + (n - frame_length) // hop_length
        idx = (jnp.arange(num)[:, None] * hop_length +
               jnp.arange(frame_length)[None, :])
        moved = jnp.moveaxis(a, axis, -1)
        framed = moved[..., idx]  # [..., num, frame_length]
        return jnp.moveaxis(framed, (-2, -1), (axis - 1 if axis < 0 else axis,
                                               axis if axis < 0 else axis + 1))

    return dispatch("frame", impl, (x,))


def overlap_add(x, hop_length, axis=-1, name=None):
    def impl(a):
        # expects [..., frames, frame_length] on the last two axes
        moved = jnp.moveaxis(a, axis, -1) if axis != -1 else a
        *batch, frames, flen = moved.shape
        out_len = (frames - 1) * hop_length + flen
        out = jnp.zeros((*batch, out_len), moved.dtype)
        for i in range(frames):  # static unroll: frames is static under jit
            out = out.at[..., i * hop_length: i * hop_length + flen].add(
                moved[..., i, :])
        return out

    return dispatch("overlap_add", impl, (x,))


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    win_arr = None if window is None else (
        window._value if isinstance(window, Tensor) else jnp.asarray(window))

    def impl(a):
        sig = a
        if center:
            pad = [(0, 0)] * (sig.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            sig = jnp.pad(sig, pad, mode=pad_mode)
        n = sig.shape[-1]
        num = 1 + (n - n_fft) // hop_length
        idx = (jnp.arange(num)[:, None] * hop_length +
               jnp.arange(n_fft)[None, :])
        frames = sig[..., idx]  # [..., num, n_fft]
        if win_arr is not None:
            w = win_arr
            if win_length < n_fft:
                lp = (n_fft - win_length) // 2
                w = jnp.pad(w, (lp, n_fft - win_length - lp))
            frames = frames * w
        spec = (jnp.fft.rfft(frames, axis=-1) if onesided
                else jnp.fft.fft(frames, axis=-1))
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        # reference layout: [..., n_freq, num_frames]
        return jnp.swapaxes(spec, -1, -2)

    return dispatch("stft", impl, (x,))


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    win_arr = None if window is None else (
        window._value if isinstance(window, Tensor) else jnp.asarray(window))

    def impl(spec_in):
        spec = jnp.swapaxes(spec_in, -1, -2)  # [..., frames, n_freq]
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        frames = (jnp.fft.irfft(spec, n=n_fft, axis=-1) if onesided
                  else jnp.fft.ifft(spec, axis=-1).real)
        w = jnp.ones((n_fft,), frames.dtype) if win_arr is None else win_arr
        if win_length < n_fft:
            lp = (n_fft - win_length) // 2
            w = jnp.pad(w, (lp, n_fft - win_length - lp))
        frames = frames * w
        *batch, num, _ = frames.shape
        out_len = (num - 1) * hop_length + n_fft
        out = jnp.zeros((*batch, out_len), frames.dtype)
        norm = jnp.zeros((out_len,), frames.dtype)
        for i in range(num):
            sl = slice(i * hop_length, i * hop_length + n_fft)
            out = out.at[..., sl].add(frames[..., i, :])
            norm = norm.at[sl].add(w * w)
        out = out / jnp.maximum(norm, 1e-8)
        if center:
            out = out[..., n_fft // 2: out.shape[-1] - n_fft // 2]
        if length is not None:
            out = out[..., :length]
        return out

    return dispatch("istft", impl, (x,))
