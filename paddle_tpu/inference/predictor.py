"""Predictor implementation (≙ AnalysisPredictor, SURVEY §3.5).

Serve path: Config names a saved model (paddle_tpu.jit.save artifact:
StableHLO program + weights); create_predictor loads it, places weights on
device once, and compiles the program AOT. ``run`` is the hot loop —
one fused XLA executable call, no Python op dispatch.
"""

from __future__ import annotations

import os
import pickle
import threading
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


class Config:
    """≙ paddle_infer.Config (analysis_config.cc).

    Knobs with real effect on this backend:

    - ``set_compilation_cache_dir`` — persistent XLA executable cache
      (≙ serialized TRT engines).
    - ``enable_memory_optim`` — donate input device buffers to the
      executable so XLA reuses them for outputs (≙ memory-reuse passes).
    - ``set_tpu_device_id`` / ``set_device_id`` — place weights and run
      on a specific local device.
    - precision is an EXPORT-TIME property on TPU: pass
      ``precision="bfloat16"`` to ``paddle.jit.save`` — the knob readers
      (``precision_mode``) report what the artifact was exported with.
    - graph passes: XLA's fixed pipeline subsumes the reference's IR pass
      registry; ``pass_builder()`` lists and deletes the REAL
      predictor-level passes (input_donation, persistent_compile_cache)
      and ``switch_ir_optim(False)`` gates them without erasing settings.
    """

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        # accept either the jit.save prefix or explicit file paths
        if prog_file is not None and prog_file.endswith(".ptpu_model"):
            self._prefix = prog_file[: -len(".ptpu_model")]
        else:
            self._prefix = prog_file
        self._params_file = params_file
        self._cache_dir: Optional[str] = None
        self._memory_optim = False
        self._glog_info = False
        self._device = None
        self._device_id = 0
        self._ir_optim = True
        self._math_threads = None

    def set_model(self, prefix: str, params_file: Optional[str] = None):
        self._prefix = prefix
        self._params_file = params_file

    def model_dir(self):
        return self._prefix

    def enable_memory_optim(self, flag: bool = True):
        """Donate input buffers to the executable (XLA reuses them)."""
        self._memory_optim = flag

    def memory_optim_enabled(self) -> bool:
        return self._effective_memory_optim()

    # switch_ir_optim(False) gates these without erasing the settings
    def _effective_memory_optim(self) -> bool:
        return bool(self._ir_optim and self._memory_optim)

    def _effective_cache_dir(self):
        return self._cache_dir if self._ir_optim else None

    def disable_glog_info(self):
        self._glog_info = False

    def set_compilation_cache_dir(self, path: str):
        """Persistent XLA executable cache (≙ TRT engine serialization)."""
        self._cache_dir = path

    def enable_tpu(self, device_id: int = 0):
        self._device = "tpu"
        self._device_id = device_id

    def set_tpu_device_id(self, device_id: int):
        self._device_id = device_id

    set_device_id = set_tpu_device_id

    def tpu_device_id(self) -> int:
        return self._device_id

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0,
                       *a, **k):  # accepted for API parity
        self._device = "tpu"
        self._device_id = device_id

    def disable_gpu(self):
        self._device = "cpu"

    def set_cpu_math_library_num_threads(self, n: int):
        self._math_threads = int(n)

    def cpu_math_library_num_threads(self) -> int:
        return self._math_threads or 1

    def switch_ir_optim(self, flag: bool = True):
        """False GATES the predictor-level program passes (donation +
        persistent compile cache) without destroying their settings —
        toggling back on restores them; XLA's own fixed pipeline always
        runs (it is the compiler, not a pass registry)."""
        self._ir_optim = flag

    def ir_optim(self) -> bool:
        return self._ir_optim

    def delete_pass(self, name: str):
        self.pass_builder().delete_pass(name)

    def pass_builder(self):
        """The passes that actually exist in this serving stack, as a
        controllable registry (the reference's 100-entry IR pass list is
        subsumed by XLA's fixed pipeline; these are the knobs ABOVE it)."""
        cfg = self

        class _PassBuilder:
            def all_passes(self):
                passes = ["xla:fixed-pipeline(fusion,layout,"
                          "rematerialization)"]
                if cfg._effective_memory_optim():
                    passes.append("input_donation")
                if cfg._effective_cache_dir():
                    passes.append("persistent_compile_cache")
                return passes

            def delete_pass(self, name):
                if name == "input_donation":
                    cfg._memory_optim = False
                elif name == "persistent_compile_cache":
                    cfg._cache_dir = None
                # the XLA fixed pipeline is not deletable (it IS the
                # compiler); unknown names are ignored like the reference

        return _PassBuilder()

    def summary(self) -> str:
        return (f"Config(model={self._prefix!r}, device={self._device}"
                f":{self._device_id}, cache_dir={self._cache_dir!r}, "
                f"memory_optim={self._memory_optim})")


class _IOHandle:
    """Zero-copy style tensor handle (≙ ZeroCopyTensor)."""

    def __init__(self, name: str, shape, dtype):
        self.name = name
        self._shape = tuple(shape)
        self._dtype = dtype
        self._array = None

    def shape(self):
        return list(self._shape)

    def copy_from_cpu(self, data: np.ndarray):
        self._array = jnp.asarray(data)

    def share_external_data(self, array):
        """True zero-copy: accept a device array without host staging."""
        self._array = getattr(array, "_value", array)

    def copy_to_cpu(self) -> np.ndarray:
        if self._array is None:
            raise RuntimeError(f"output {self.name!r} not produced yet; "
                               "call predictor.run() first")
        return np.asarray(self._array)

    def to_device_array(self):
        return self._array


class Predictor:
    def __init__(self, config: Config, _shared=None):
        self.config = config
        if _shared is not None:
            (self._exported, self._param_values, self._in_spec,
             self._compiled, self._precision, self._donating) = _shared
        else:
            prefix = config.model_dir()
            if prefix is None:
                raise ValueError("Config has no model path")
            if config._effective_cache_dir():
                os.makedirs(config._effective_cache_dir(), exist_ok=True)
                jax.config.update("jax_compilation_cache_dir",
                                  config._effective_cache_dir())
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0.0)
            from jax import export as jax_export
            with open(prefix + ".ptpu_model", "rb") as f:
                self._exported = jax_export.deserialize(f.read())
            with open(prefix + ".ptpu_params", "rb") as f:
                meta = pickle.load(f)
            device = None
            try:
                devices = jax.devices()
                if 0 <= config._device_id < len(devices):
                    device = devices[config._device_id]
            except Exception:
                pass
            self._param_values = [
                jax.device_put(jnp.asarray(v), device) if device is not None
                else jnp.asarray(v) for v in meta["values"]]
            self._in_spec = meta["in_spec"]
            self._precision = meta.get("precision")
            exported = self._exported
            jit_kwargs = {}
            # SNAPSHOT the donation decision: it is baked into the
            # compiled executable, so run() must not re-read the mutable
            # config (a post-create switch_ir_optim(False) would skip
            # the defensive input copies while XLA still donates)
            self._donating = bool(config._effective_memory_optim()
                                  and self._in_spec)
            if self._donating:
                # donate input buffers: XLA may write outputs in place
                jit_kwargs["donate_argnums"] = tuple(
                    range(1, 1 + len(self._in_spec)))
            self._compiled = jax.jit(
                lambda pv, *ins: exported.call(pv, *ins), **jit_kwargs)
        self._precision = getattr(self, "_precision", None)
        self._inputs: Dict[str, _IOHandle] = {}
        self._outputs: Dict[str, _IOHandle] = {}
        self._out_values: Optional[tuple] = None
        self._lock = threading.Lock()
        for i, (shape, dtype) in enumerate(self._in_spec):
            name = f"input_{i}"
            self._inputs[name] = _IOHandle(name, shape, dtype)

    # -- reference API surface --
    def get_input_names(self) -> List[str]:
        return list(self._inputs)

    def get_input_handle(self, name: str) -> _IOHandle:
        return self._inputs[name]

    def get_output_names(self) -> List[str]:
        self._ensure_ran()
        return list(self._outputs)

    def get_output_handle(self, name: str) -> _IOHandle:
        self._ensure_ran()
        return self._outputs[name]

    def run(self, inputs: Optional[List] = None):
        """Execute the compiled program. Either feed via input handles
        (reference style) or pass arrays directly and get arrays back."""
        donating = self._donating
        if inputs is not None:
            arrays = [getattr(a, "_value", None) if hasattr(a, "_value")
                      else jnp.asarray(a) for a in inputs]
            arrays = [a if a is not None else jnp.asarray(b)
                      for a, b in zip(arrays, inputs)]
            if donating:
                # donation invalidates the fed buffers; callers own these
                # arrays (paddle Tensors), so feed defensive copies
                arrays = [jnp.array(a, copy=True) for a in arrays]
        else:
            arrays = []
            for name, h in self._inputs.items():
                if h._array is None:
                    raise RuntimeError(f"input {name!r} not set; call "
                                       "copy_from_cpu first")
                arrays.append(h._array)
            if donating:
                # staged device buffers are predictor-owned (copy_from_cpu
                # staged them); mark them consumed so a second run()
                # cannot feed donated (deleted) buffers
                for h in self._inputs.values():
                    h._array = None
        with self._lock:
            out = self._compiled(self._param_values, *arrays)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        self._outputs = {}
        for i, o in enumerate(outs):
            h = _IOHandle(f"output_{i}", o.shape, o.dtype)
            h._array = o
            self._outputs[h.name] = h
        self._out_values = tuple(outs)
        if inputs is not None:
            return [np.asarray(o) for o in outs]
        return True

    def _ensure_ran(self):
        if not self._outputs:
            # run lazily if inputs are staged (reference returns names after
            # graph load; we materialize them on first demand)
            raise RuntimeError("no outputs yet; call run() first")

    def precision_mode(self) -> Optional[str]:
        """Export-time compute precision of the loaded artifact (set via
        paddle.jit.save(precision=...)); None = full precision."""
        return self._precision

    def clone(self) -> "Predictor":
        """Share weights + executable with a new handle (per-thread serving,
        ≙ AnalysisPredictor::Clone)."""
        return Predictor(self.config,
                         _shared=(self._exported, self._param_values,
                                  self._in_spec, self._compiled,
                                  self._precision, self._donating))


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
