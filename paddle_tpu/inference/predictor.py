"""Predictor implementation (≙ AnalysisPredictor, SURVEY §3.5).

Serve path: Config names a saved model (paddle_tpu.jit.save artifact:
StableHLO program + weights); create_predictor loads it, places weights on
device once, and compiles the program AOT. ``run`` is the hot loop —
one fused XLA executable call, no Python op dispatch.
"""

from __future__ import annotations

import os
import pickle
import threading
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


class Config:
    """≙ paddle_infer.Config (analysis_config.cc)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        # accept either the jit.save prefix or explicit file paths
        if prog_file is not None and prog_file.endswith(".ptpu_model"):
            self._prefix = prog_file[: -len(".ptpu_model")]
        else:
            self._prefix = prog_file
        self._params_file = params_file
        self._cache_dir: Optional[str] = None
        self._memory_optim = True
        self._glog_info = False
        self._device = None

    def set_model(self, prefix: str, params_file: Optional[str] = None):
        self._prefix = prefix
        self._params_file = params_file

    def model_dir(self):
        return self._prefix

    def enable_memory_optim(self, flag: bool = True):
        self._memory_optim = flag

    def disable_glog_info(self):
        self._glog_info = False

    def set_compilation_cache_dir(self, path: str):
        """Persistent XLA executable cache (≙ TRT engine serialization)."""
        self._cache_dir = path

    def enable_tpu(self):
        self._device = "tpu"

    def enable_use_gpu(self, *a, **k):  # accepted for API parity
        self._device = "tpu"

    def disable_gpu(self):
        self._device = "cpu"

    def summary(self) -> str:
        return (f"Config(model={self._prefix!r}, device={self._device}, "
                f"cache_dir={self._cache_dir!r})")


class _IOHandle:
    """Zero-copy style tensor handle (≙ ZeroCopyTensor)."""

    def __init__(self, name: str, shape, dtype):
        self.name = name
        self._shape = tuple(shape)
        self._dtype = dtype
        self._array = None

    def shape(self):
        return list(self._shape)

    def copy_from_cpu(self, data: np.ndarray):
        self._array = jnp.asarray(data)

    def share_external_data(self, array):
        """True zero-copy: accept a device array without host staging."""
        self._array = getattr(array, "_value", array)

    def copy_to_cpu(self) -> np.ndarray:
        if self._array is None:
            raise RuntimeError(f"output {self.name!r} not produced yet; "
                               "call predictor.run() first")
        return np.asarray(self._array)

    def to_device_array(self):
        return self._array


class Predictor:
    def __init__(self, config: Config, _shared=None):
        self.config = config
        if _shared is not None:
            (self._exported, self._param_values, self._in_spec,
             self._compiled) = _shared
        else:
            prefix = config.model_dir()
            if prefix is None:
                raise ValueError("Config has no model path")
            if config._cache_dir:
                os.makedirs(config._cache_dir, exist_ok=True)
                jax.config.update("jax_compilation_cache_dir",
                                  config._cache_dir)
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0.0)
            from jax import export as jax_export
            with open(prefix + ".ptpu_model", "rb") as f:
                self._exported = jax_export.deserialize(f.read())
            with open(prefix + ".ptpu_params", "rb") as f:
                meta = pickle.load(f)
            self._param_values = [jnp.asarray(v) for v in meta["values"]]
            self._in_spec = meta["in_spec"]
            exported = self._exported
            self._compiled = jax.jit(
                lambda pv, *ins: exported.call(pv, *ins))
        self._inputs: Dict[str, _IOHandle] = {}
        self._outputs: Dict[str, _IOHandle] = {}
        self._out_values: Optional[tuple] = None
        self._lock = threading.Lock()
        for i, (shape, dtype) in enumerate(self._in_spec):
            name = f"input_{i}"
            self._inputs[name] = _IOHandle(name, shape, dtype)

    # -- reference API surface --
    def get_input_names(self) -> List[str]:
        return list(self._inputs)

    def get_input_handle(self, name: str) -> _IOHandle:
        return self._inputs[name]

    def get_output_names(self) -> List[str]:
        self._ensure_ran()
        return list(self._outputs)

    def get_output_handle(self, name: str) -> _IOHandle:
        self._ensure_ran()
        return self._outputs[name]

    def run(self, inputs: Optional[List] = None):
        """Execute the compiled program. Either feed via input handles
        (reference style) or pass arrays directly and get arrays back."""
        if inputs is not None:
            arrays = [getattr(a, "_value", None) if hasattr(a, "_value")
                      else jnp.asarray(a) for a in inputs]
            arrays = [a if a is not None else jnp.asarray(b)
                      for a, b in zip(arrays, inputs)]
        else:
            arrays = []
            for name, h in self._inputs.items():
                if h._array is None:
                    raise RuntimeError(f"input {name!r} not set; call "
                                       "copy_from_cpu first")
                arrays.append(h._array)
        with self._lock:
            out = self._compiled(self._param_values, *arrays)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        self._outputs = {}
        for i, o in enumerate(outs):
            h = _IOHandle(f"output_{i}", o.shape, o.dtype)
            h._array = o
            self._outputs[h.name] = h
        self._out_values = tuple(outs)
        if inputs is not None:
            return [np.asarray(o) for o in outs]
        return True

    def _ensure_ran(self):
        if not self._outputs:
            # run lazily if inputs are staged (reference returns names after
            # graph load; we materialize them on first demand)
            raise RuntimeError("no outputs yet; call run() first")

    def clone(self) -> "Predictor":
        """Share weights + executable with a new handle (per-thread serving,
        ≙ AnalysisPredictor::Clone)."""
        return Predictor(self.config,
                         _shared=(self._exported, self._param_values,
                                  self._in_spec, self._compiled))


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
