"""paddle_tpu.inference — the deployment/serving path.

TPU-native analogue of the reference inference engine (SURVEY §2.8:
``AnalysisPredictor`` at paddle/fluid/inference/api/analysis_predictor.h:94
with Config, zero-copy IO handles, clone-per-thread). The redesign:

- graph optimization (the 276 IR fuse passes + TensorRT subgraphs) is XLA's
  job — the saved artifact is StableHLO, compiled AOT on first use and
  cached persistently (jax compilation cache ≙ serialized TRT engines);
- zero-copy IO maps to device arrays handed in/out without host staging;
- ``Predictor.clone()`` shares weights between handles (≙
  AnalysisPredictor::Clone for multi-thread serving).
"""

from .predictor import Config, Predictor, create_predictor  # noqa: F401
from .llm import LLMPredictor  # noqa: F401
from .serving import (AdmissionError, EngineStalledError,  # noqa: F401
                      PoisonedDispatchError, ReplicaKilledError,
                      Request, ServingEngine, TokenStream)
from .faultinject import FaultInjector  # noqa: F401
from .prefixcache import HostTier, RadixPrefixCache  # noqa: F401
from .speculative import (Drafter, ModelDrafter,  # noqa: F401
                          NGramDrafter)
from .lora import AdapterStore, LoraAdapter  # noqa: F401
from .router import (HEALTH_STATES, ROUTER_POLICIES,  # noqa: F401
                     RoutedRequest, Router)
from .transport import (FRAME_KINDS, LoopbackTransport,  # noqa: F401
                        RemoteReplica, SocketTransport,
                        TransportDeadError, TransportError,
                        WIRE_VERSION)
from .procserve import (EngineHost, EngineProcess,  # noqa: F401
                        TCPStoreLite)

__all__ = ["Config", "Predictor", "create_predictor", "LLMPredictor",
           "Request", "ServingEngine", "TokenStream", "Drafter",
           "NGramDrafter", "ModelDrafter", "AdmissionError",
           "EngineStalledError", "ReplicaKilledError",
           "PoisonedDispatchError", "FaultInjector", "HostTier",
           "RadixPrefixCache", "AdapterStore", "LoraAdapter",
           "Router", "RoutedRequest", "ROUTER_POLICIES",
           "HEALTH_STATES", "FRAME_KINDS", "WIRE_VERSION",
           "LoopbackTransport", "SocketTransport", "RemoteReplica",
           "TransportError", "TransportDeadError", "EngineHost",
           "EngineProcess", "TCPStoreLite"]
