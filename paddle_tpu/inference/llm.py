"""LLM serving: a KV-cache decode session for the Predictor stack.

Reference analogue: the fused decode-serving path —
``paddle/fluid/operators/fused/fused_multi_transformer_op.cu`` (+ its
int8 twin) driven step-by-step under AnalysisPredictor with persistent
cache tensors.  TPU formulation:

- ``LLMPredictor`` owns the session state (token, lengths, done flags,
  per-layer KV buffers) as device arrays between calls — the session is
  the cache's lifetime, like the reference's cache_kv variables living
  in the predictor scope.
- Decode runs in BLOCKS of ``steps_per_call`` tokens: one compiled call
  (``lax.scan`` inside) emits K tokens, so the per-dispatch cost
  (~6-10 ms through the axon tunnel) amortizes over K steps while the
  session stays incremental.  The float->compute-dtype weight cast also
  amortizes per block.
- ``save()`` exports the prefill and decode-block programs as portable
  StableHLO (jax.export, same mechanism as ``paddle.jit.save``) plus a
  weights pickle; ``LLMPredictor.load()`` rebuilds the session without
  the model's Python class.  Artifacts carry the FULL decode
  configuration: greedy, sampled (temperature/top-k with the PRNG key
  threaded through the block programs), or beam search (the block ships
  per-step token/parent/score planes; the host backtraces the beam tree
  with ``gather_tree`` once at the end).
"""

from __future__ import annotations

import contextlib
import os
import pickle
from typing import List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.generation import (GenerationConfig, beam_scan_body,
                                 decode_scan_body, init_kv_cache,
                                 model_arrays, sample_token, swap_call,
                                 _gather_tree_arrays)


def _flatten_kvs(kvs):
    flat = []
    for k, v in kvs:
        flat.append(k)
        flat.append(v)
    return flat


def _unflatten_kvs(flat):
    return [(flat[i], flat[i + 1]) for i in range(0, len(flat), 2)]


def normalize_weight_dtype(weight_dtype):
    """Validate a ``weight_dtype=`` argument.  Returns ``None`` for
    full-precision serving (``None`` or any float dtype name — weights
    then stream at the compute dtype, today's behavior) or the
    canonical ``"int8"``/``"int4"`` string for quantized weight planes.
    The allowed set is deliberately distinct from ``kv_cache_dtype``'s
    (which admits float dtypes or ``"int8"`` only)."""
    if weight_dtype is None:
        return None
    s = str(weight_dtype)
    if s in ("int8", "int4"):
        return s
    try:
        dt = jnp.dtype(weight_dtype)
    except TypeError:
        raise ValueError(
            f"weight_dtype must be a float dtype (full-precision "
            f"weights), 'int8' or 'int4' (quantized code+scale planes); "
            f"got {weight_dtype!r}")
    if jnp.issubdtype(dt, jnp.floating):
        return None
    raise ValueError(
        f"weight_dtype must be a float dtype, 'int8' or 'int4'; got "
        f"{weight_dtype!r} — integer weight arenas other than int8/int4 "
        "have no code+scale discipline")


class WeightQuantPlan:
    """One model's quantized-weight planes plus the bookkeeping that
    threads them through the serving programs: per (layer_idx, target)
    an int8 code plane ([K, N]; int4 packs to [K//2, N]) and a
    per-output-channel f32 scale plane [N], calibrated through
    ``quantization.observers`` (the ONE quant rule — see
    ``absmax_to_scales``).  ``flat_values()`` appends to the engine's
    swapped param/buffer list (ONE positional list argument, so donation
    index tuples never shift); ``bind()`` rebuilds the trace-time
    context from the traced values inside a program."""

    def __init__(self, dtype_str, bits, entries, max_m=256):
        self.dtype = dtype_str
        self.bits = bits
        # entries: (layer_idx, target, param_pos, codes, scales) in
        # deterministic (layer, declaration) order
        self.entries = entries
        self.max_m = max_m
        self.param_positions = frozenset(e[2] for e in entries)

    def flat_values(self):
        flat = []
        for _li, _t, _pos, codes, scales in self.entries:
            flat.append(codes)
            flat.append(scales)
        return flat

    def bind(self, flat):
        from ..models.wquant import WeightQuantContext
        planes = {}
        for i, (li, t, _pos, _c, _s) in enumerate(self.entries):
            planes[(li, t)] = (flat[2 * i], flat[2 * i + 1])
        return WeightQuantContext(planes, self.bits, self.max_m)

    def bytes_swept(self):
        """Modeled HBM bytes one forward streams for the quantized
        planes (codes at their packed width + f32 scales)."""
        return sum(int(c.nbytes) + int(s.nbytes)
                   for _li, _t, _pos, c, s in self.entries)

    def placeholder_params(self, params):
        """The swapped param value list with every quantized weight's
        slot replaced by a ZERO-SIZE placeholder: a projection site that
        fails to divert through ``wq_linear`` hits a shape error at
        trace time instead of silently streaming a stale float plane."""
        return [jnp.zeros((0,), p._value.dtype)
                if i in self.param_positions else p._value
                for i, p in enumerate(params)]


def build_weight_quant_plan(model, weight_dtype) -> WeightQuantPlan:
    """Quantize ``model``'s hot projections once at load.  Scales go
    through the PerChannelAbsmaxObserver path (``quantization/
    observers.py``) so PTQ calibration and the serving loader share one
    bit-exact rule; codes are ``quantize_channelwise`` of the same rule;
    int4 packs two codes per byte (``pack_int4``)."""
    from ..nn import Linear
    from ..quantization.observers import (PerChannelAbsmaxObserver,
                                          absmax_to_scales,
                                          quantize_channelwise)
    from ..ops.pallas.quantized_matmul import pack_int4
    bits = {"int8": 8, "int4": 4}[weight_dtype]
    if not hasattr(model, "quant_projections"):
        raise ValueError(
            f"weight_dtype={weight_dtype!r} needs a model exposing "
            "quant_projections() (llama/gpt); got "
            f"{type(model).__name__}")
    params, _buffers = model_arrays(model)
    pos = {id(p): i for i, p in enumerate(params)}
    entries = []
    for li, layer in enumerate(model.quant_projections()):
        for target, lin in layer.items():
            if not isinstance(lin, Linear):
                raise ValueError(
                    f"weight_dtype={weight_dtype!r} supports plain "
                    f"nn.Linear projections only; layer {li} {target} is "
                    f"{type(lin).__name__} (tensor-parallel serving "
                    "quantization is not wired)")
            obs = PerChannelAbsmaxObserver(quant_axis=-1, bit_length=bits)
            obs.observe(lin.weight)
            scales = absmax_to_scales(obs.scales()._value, bits)
            codes = quantize_channelwise(lin.weight._value, scales, bits,
                                         quant_axis=-1)
            if bits == 4:
                codes = pack_int4(codes)
            entries.append((li, target, pos[id(lin.weight)],
                            codes, scales))
    return WeightQuantPlan(weight_dtype, bits, entries)


def _param_swapper(model, cfg: GenerationConfig, wq=None):
    """The closure every serving program shares: positional
    params+buffers values in, the model's weights swapped for the traced
    arrays for the duration of the call (floats cast ONCE to the serving
    compute dtype — the hoisted fast-layout copy).

    ``wq`` (a WeightQuantPlan) appends the quantized code/scale planes
    to the SAME positional list: the trailing ``2 * len(entries)``
    values are split off, bound into a trace-time wquant context
    (``models/wquant.py``), and the projection sites route through them
    — the core params at quantized positions are zero-size placeholders
    that fail loudly if any site misses the diversion."""
    params, buffers = model_arrays(model)

    if wq is None:
        def _with_params(pb_values, fn):
            p_values = pb_values[:len(params)]
            b_values = pb_values[len(params):]
            return swap_call(params, buffers, p_values, b_values,
                             cfg.compute_dtype, fn)
        return _with_params

    from ..models.wquant import wquant_context
    n_core = len(params) + len(buffers)

    def _with_params_wq(pb_values, fn):
        core = pb_values[:n_core]
        ctx = wq.bind(list(pb_values[n_core:]))
        p_values = core[:len(params)]
        b_values = core[len(params):]

        def run():
            with wquant_context(ctx):
                return fn()
        return swap_call(params, buffers, p_values, b_values,
                         cfg.compute_dtype, run)

    return _with_params_wq


def _build_decode_block(model, cfg: GenerationConfig, steps_per_call,
                        wq=None):
    """Pure greedy/sampled decode block: ``lax.scan`` of
    ``steps_per_call`` steps of the shared ``decode_scan_body``.

    Slot-granular serving contract (ServingEngine): every op in the
    body is row-independent — per-row cache scatter, per-row prefix
    attention, per-row EOS/length masking — so a batch row decodes
    identically whatever mix of fill levels the other slots hold.
    Occupancy is pure DATA (``lens``/``done`` vectors), never shape:
    one compiled block serves every occupancy mix, and rows with
    ``done=True`` freeze (lens stops advancing, emits are pad), which
    is how both finished and vacant slots ride along for free.
    ``wq`` (a WeightQuantPlan) appends quantized code/scale planes to
    the positional param list — see ``_param_swapper``.
    """
    _with_params = _param_swapper(model, cfg, wq=wq)

    def block_pure(p_values, tok, lens, done, key, *flat_kvs):
        def run():
            kvs = _unflatten_kvs(list(flat_kvs))
            (tok_f, lens_f, kvs_f, key_f, done_f), toks = jax.lax.scan(
                decode_scan_body(model, cfg), (tok, lens, kvs, key, done),
                None, length=steps_per_call)
            return ((toks.T.astype(jnp.int32), tok_f, lens_f, done_f,
                     key_f) + tuple(_flatten_kvs(kvs_f)))
        return _with_params(p_values, run)

    return block_pure


def build_slot_prefill(model, max_cache_len, cfg: GenerationConfig):
    """Slot-granular prefill for continuous batching (ServingEngine):
    prefill ONE sequence (a batch-1 compiled prompt pass) and write its
    K/V into row ``slot`` of a shared B-slot cache pool.

    The whole ``max_cache_len`` cache row is written — prompt K/V
    followed by the zeros of the batch-1 scratch cache — so admission
    unconditionally scrubs the previous occupant's stale K/V (defense
    in depth on top of the ``lens`` masking that already hides slots
    past the valid prefix).  ``slot`` is a TRACED scalar: one compiled
    program admits into any slot.  Signature:
    ``(p_values, slot, ids [1, P], lens [1], key, *flat_kvs) ->
    (tok0 [1], key', *flat_kvs)``.
    """
    if cfg.num_beams > 1:
        raise ValueError(
            "slot-granular prefill is greedy/sampled only — beam search "
            "expands to K cache rows per request, which does not fit a "
            "one-slot-per-request pool")
    n_layers, hkv, d = model.kv_cache_spec()
    cache_dtype = jnp.dtype(cfg.cache_dtype or cfg.compute_dtype)
    _with_params = _param_swapper(model, cfg)

    def slot_prefill_pure(p_values, slot, ids, lens, key, *flat_kvs):
        def run():
            small = init_kv_cache(n_layers, 1, max_cache_len, hkv, d,
                                  cache_dtype)
            logits, small = model.prefill(ids, lens, small)
            if cfg.do_sample:
                key0, keyr = jax.random.split(key)
            else:
                key0 = keyr = key
            tok0 = sample_token(logits, key0, cfg)
            big = _unflatten_kvs(list(flat_kvs))
            out = []
            for (bk, bv), (sk, sv) in zip(big, small):
                zero = (0,) * (bk.ndim - 1)
                out.append((
                    jax.lax.dynamic_update_slice(bk, sk, (slot,) + zero),
                    jax.lax.dynamic_update_slice(bv, sv, (slot,) + zero)))
            return (tok0, keyr) + tuple(_flatten_kvs(out))
        return _with_params(p_values, run)

    return slot_prefill_pure


class ArenaSharding(NamedTuple):
    """Mesh recipe for tensor-parallel paged serving: every arena plane
    (float K/V, int8 codes, AND their f32 scale planes) shards its
    LAST axis — kv-heads; ``Hkv*D`` for packed planes, ``Hkv`` for
    scales — over the mesh's ``model`` axis, so one ``NamedSharding``
    covers all of them and each shard owns ``Hkv / n_shards`` whole
    heads (the engine enforces the divisibility).  Block tables,
    token/length/done planes and sampling state stay replicated: the
    byte-deterministic host plan is the SAME program input on every
    shard, which is what keeps scheduling identical to single-chip.
    ``n_shards`` rides along so trace-time code (the kernel route
    gate) can report the shard geometry without re-deriving it from
    the sharding object."""
    kv: object        # jax.sharding.NamedSharding over the arena axes
    n_shards: int


def _shard_scope(shard):
    """Trace-time marker: inside this scope the paged kernel gates
    report the ``sharded_ok``/``mesh_geom`` route overlay (see
    ``ops/pallas/decode_attention.shard_dispatch_scope``).  A ``None``
    shard is the single-chip build — no scope, no overlay counters."""
    if shard is None:
        return contextlib.nullcontext()
    from ..ops.pallas import decode_attention as _da
    return _da.shard_dispatch_scope(shard.n_shards)


def _constrain_arenas(flat, shard):
    """Pin every arena plane to the shard recipe inside a traced
    program (``with_sharding_constraint``): on the way IN it makes
    GSPMD propagation decisive through the scan carry, on the way OUT
    it guarantees the donated round-trip keeps the input sharding
    (donation only reuses buffers when in/out layouts match — an
    unconstrained output that propagated to replicated would silently
    re-shard every dispatch).  No-op for single-chip builds."""
    if shard is None:
        return list(flat)
    return [jax.lax.with_sharding_constraint(a, shard.kv) for a in flat]


def _pack_paged_kvs(flat_arenas, tables, kv_int8):
    """Per-layer kv entries from the engine's flat arena list: the
    (k, v, tables) triple of the float cache, or the
    (k_codes, v_codes, k_scales, v_scales, tables) 5-tuple of the int8
    cache (4 donated arrays per layer instead of 2)."""
    stride = 4 if kv_int8 else 2
    return [tuple(flat_arenas[i:i + stride]) + (tables,)
            for i in range(0, len(flat_arenas), stride)]


def _flatten_paged_kvs(kvs):
    """Inverse of ``_pack_paged_kvs`` minus the tables: the flat arena
    list handed back out of a serving program (donation-matched)."""
    flat = []
    for entry in kvs:
        flat += list(entry[:-1])
    return flat


def _build_paged_decode_block(model, cfg: GenerationConfig, steps_per_call,
                              kv_int8=False,
                              samp_flags=(False, False, False, False),
                              lora=False, wq=None, shard=None):
    """Paged twin of ``_build_decode_block``: the cache is the shared
    block arena plus per-slot block tables instead of per-slot
    contiguous rows.  The tables ride into the scan closure as a
    loop-invariant traced value (a request's table never changes during
    its decode life — all its blocks are mapped at admission), so the
    per-step transfer is ONLY the small [B, max_blocks] int32 table
    push; the arenas stay donated device buffers.  ``kv_int8`` selects
    the quantized cache: ``flat_arenas`` then interleaves
    (k_codes, v_codes, k_scales, v_scales) per layer and the models'
    decode path quantizes on append / dequantizes on read.

    ``samp_flags = (sampled, filtered, penalty, bias)`` statically selects the
    per-row sampling machinery (``inference/sampling.py``): the
    all-False build is the exact greedy program (argmax only), and each
    flag compiles in only the planes its mix needs — the ``samp``
    pytree's structure is determined by the same flags, so program
    variants and plane dicts stay in lockstep.  Signature:
    ``(p_values, tok, lens, done, budget, samp, tables, *flat_arenas)
    -> (toks [B, n], tok', lens', done', budget', *flat_arenas)``.

    Dispatch-ahead contract: every output is an UN-MATERIALIZED
    device array (JAX async dispatch) and the carries ``tok'``/
    ``lens'``/``done'``/``budget'`` are valid INPUTS to the next block
    call as-is — the caller may enqueue iteration N+1 feeding them
    directly and force iteration N's outputs to host afterwards (the
    ServingEngine plan/harvest split).  Done rows self-freeze in-trace
    (pad emits, held lens), which is what makes one-step-stale host
    truth safe.  ``done'`` is the IN-TRACE FINISH BITMAP: it flips on
    an emitted EOS *and* on budget exhaustion (``budget`` [B] int32 is
    the per-row remaining-token count, decremented per live emit), so
    a depth-S pipeline can keep dispatching on stale truth and poll
    the bitmap at harvest instead of syncing every iteration — see
    ``serving.ASYNC_SYNC_REASONS`` for where a sync is still
    semantically required.

    ``lora=True`` compiles the batched multi-adapter variant: a
    ``lora`` pytree argument (``{"ids": [B] int32, "a"/"b": {target:
    stacked arena}}``) is inserted after ``samp`` and the scan traces
    under an active adapter context (``models/lora.py``) — per-row
    gathered A/B einsums add each request's low-rank delta inside the
    attention projections.  The gather is hoisted out of the scan
    (ids are loop-invariant), and the ``lora=False`` build keeps
    today's exact signature and program.

    ``wq`` (a WeightQuantPlan) selects quantized-weight serving: the
    plan's code/scale planes ride as trailing entries of ``p_values``
    (one positional list — donation indices over the trailing arena
    args never shift) and the scan traces under an active weight-quant
    context (``models/wquant.py``)."""
    from .sampling import sampled_decode_scan_body
    from ..models.lora import gather_lora, lora_context
    _with_params = _param_swapper(model, cfg, wq=wq)
    sampled, _filtered, penalty, _bias = samp_flags

    def _scan(tok, lens, done, budget, samp, tables, flat_arenas):
        kvs = _pack_paged_kvs(_constrain_arenas(flat_arenas, shard),
                              tables, kv_int8)
        pos0 = samp["pos"] if sampled else jnp.zeros_like(lens)
        pres0 = samp["presence"] if penalty else None
        with _shard_scope(shard):
            (tok_f, lens_f, kvs_f, _pos_f, _pres_f, done_f, budget_f), \
                toks = jax.lax.scan(
                    sampled_decode_scan_body(model, cfg, samp, samp_flags),
                    (tok, lens, kvs, pos0, pres0, done, budget),
                    None, length=steps_per_call)
        return ((toks.T.astype(jnp.int32), tok_f, lens_f, done_f,
                 budget_f) + tuple(_constrain_arenas(
                     _flatten_paged_kvs(kvs_f), shard)))

    if lora:
        def block_pure(p_values, tok, lens, done, budget, samp,
                       lora_planes, tables, *flat_arenas):
            def run():
                with lora_context(gather_lora(lora_planes)):
                    return _scan(tok, lens, done, budget, samp, tables,
                                 flat_arenas)
            return _with_params(p_values, run)
    else:
        def block_pure(p_values, tok, lens, done, budget, samp, tables,
                       *flat_arenas):
            return _with_params(
                p_values,
                lambda: _scan(tok, lens, done, budget, samp, tables,
                              flat_arenas))

    return block_pure


def build_fused_decode_window(model, cfg: GenerationConfig,
                              steps_per_iter, iters, **build_kw):
    """Fused multi-iteration decode dispatch (PR 14): ``iters``
    scheduler iterations of a ``steps_per_iter``-step decode block as
    ONE compiled program — the ``steps_per_call`` amortization of
    ``decode_scan_body`` lifted from intra-block to inter-iteration.

    Because the per-token scan body already self-feeds its carries
    (done rows freeze in-trace; the finish bitmap flips on EOS and
    budget exhaustion), S iterations of an n-step block ARE one
    ``lax.scan`` of S*n steps: the builder reuses
    ``_build_paged_decode_block`` with ``steps_per_call = S * n``, so
    a fused window and a plain (S*n)-step block share one compiled
    program (the engine's block cache keys on total steps).

    This is NOT ``steps_per_call=S*n`` at the engine level:
    ``steps_per_call`` is a static engine-wide granularity the
    scheduler must honor every iteration (and drops to 1 whenever a
    budget could exhaust mid-block), while a fused window is a
    PER-ITERATION choice the plan phase makes only when the window is
    provably eventless (no chunk-final, no mask/penalty rows, no spec,
    no queue, budget headroom > S*n for every rider) — and the harvest
    still accounts the window as S logical iterations (per-iteration
    flight-recorder events, ledger splits and KV-sweep modeling), so
    token streams and per-request stories stay iteration-exact."""
    return _build_paged_decode_block(
        model, cfg, int(steps_per_iter) * int(iters), **build_kw)


def build_swap_out_gather(shard=None):
    """Swap-out reader for the host-RAM block tier (ServingEngine):
    gather a row of block ids out of EVERY arena in one compiled call
    — ``(ids [W], *flat_arenas) -> tuple of [W, ...] row stacks``.
    Two consumers share ONE compiled shape (``W = max_blocks``,
    trash-padded): preemption gathers a slot's full table row, and the
    tiered prefix cache demotes each alloc's reclaimed batch through
    the same program (wider reclaims page through it) — demotion costs
    a dispatch per admission, not per block, and adds no second
    compile.  The gathered rows are the EXACT at-rest bytes of
    the blocks — float K/V, or int8 codes plus their f32 scale planes,
    whichever the arena holds — which is what makes preempt/resume
    (and a host-tier prefix hit) byte-identical rather than
    recompute-and-hope.  Trash-row gathers past the allocation are
    finite garbage the resume scatter routes straight back to the
    trash row."""
    def gather_pure(ids, *flat_arenas):
        return tuple(jnp.take(a, ids, axis=0)
                     for a in _constrain_arenas(flat_arenas, shard))
    return gather_pure


def build_swap_in_scatter(n_arenas, shard=None):
    """Donation-matched re-scatter for host-RAM -> arena restores:
    write saved block rows into freshly allocated arena rows —
    ``(ids [W], *rows (n_arenas of [W, ...]), *flat_arenas) ->
    flat_arenas`` with the arenas donated, same discipline as the
    decode/chunk/verify programs (steady-state serving never
    materializes a second arena copy).  ONE compiled program serves
    both preemption RESUME and the tiered prefix cache's host-hit
    promotion (``W = max_blocks`` for both; promotion packs its k
    parcels into the leading rows).  ``ids`` is the destination row:
    entries past the payload point at the trash row, so pad rows of
    the saved stack land there (the write-masking contract of every
    other paged writer) and duplicate trash writes only ever
    overwrite finite garbage with finite garbage."""
    def scatter_pure(ids, *rows_and_arenas):
        rows = rows_and_arenas[:n_arenas]
        arenas = _constrain_arenas(rows_and_arenas[n_arenas:], shard)
        return tuple(_constrain_arenas(
            [a.at[ids].set(r.astype(a.dtype))
             for a, r in zip(arenas, rows)], shard))
    return scatter_pure


def build_chunk_prefill(model, cfg: GenerationConfig, kv_int8=False,
                        samp_flags=(False, False, False, False),
                        lora=False, wq=None, shard=None):
    """Chunked-prefill program for the paged ServingEngine: ONE prompt
    chunk of ONE sequence (batch-1; the static chunk length is the ids
    shape) computed at global positions ``start .. start+C-1``, K/V
    written through the slot's block table (``models.*.prefill_chunk``).
    A token is sampled from the logits at prompt position
    ``n_valid - 1`` every call; it is only meaningful on the chunk that
    covers that position — the engine ignores earlier chunks' sample
    and never advances decode state from them.  ``kv_int8`` selects the
    quantized cache and ``samp_flags`` the per-request sampling
    machinery (see ``_build_paged_decode_block``; the batch-1 ``samp``
    planes carry the request's params at PRNG position 0 — the
    first output token's draw is chunk-layout- and prefix-hit-
    independent by construction).  Signature:
    ``(p_values, ids [1, C], start [], n_valid [], tables
    [1, max_blocks], samp, *flat_arenas) -> (tok [1],
    *flat_arenas)``.

    Dispatch-ahead contract: the outputs are un-materialized device
    arrays; only the FINAL chunk's ``tok`` is host truth (the
    request's first token), so the engine forces exactly that one —
    non-final chunks are pure enqueues whose compute overlaps
    subsequent host scheduling.

    ``lora=True`` inserts a ``lora`` pytree argument after ``samp``
    (batch-1 ids: the request's adapter slot) and traces the chunk
    under an active adapter context — so a LoRA request's PROMPT K/V
    is computed through its adapter too, exactly what its merged-
    weights twin would have written (see ``_build_paged_decode_block``
    for the plane layout; ``lora=False`` keeps today's program).
    ``wq`` selects quantized-weight serving (see
    ``_build_paged_decode_block``) — the prompt pass runs through the
    same codes+scales the decode blocks do."""
    if cfg.num_beams > 1:
        raise ValueError(
            "chunked prefill is greedy/sampled only — beam search "
            "expands to K cache rows per request, which does not fit a "
            "one-slot-per-request block table")
    from .sampling import sample_rows
    from ..models.lora import gather_lora, lora_context
    _with_params = _param_swapper(model, cfg, wq=wq)
    penalty = samp_flags[2]

    def _chunk(ids, start, n_valid, tables, samp, flat_arenas):
        kvs = _pack_paged_kvs(_constrain_arenas(flat_arenas, shard),
                              tables, kv_int8)
        with _shard_scope(shard):
            logits, kvs_f = model.prefill_chunk(ids, start, n_valid, kvs)
        tok = sample_rows(logits, samp, samp_flags,
                          samp["presence"] if penalty else None)
        return (tok,) + tuple(_constrain_arenas(
            _flatten_paged_kvs(kvs_f), shard))

    if lora:
        def chunk_pure(p_values, ids, start, n_valid, tables, samp,
                       lora_planes, *flat_arenas):
            def run():
                with lora_context(gather_lora(lora_planes)):
                    return _chunk(ids, start, n_valid, tables, samp,
                                  flat_arenas)
            return _with_params(p_values, run)
    else:
        def chunk_pure(p_values, ids, start, n_valid, tables, samp,
                       *flat_arenas):
            return _with_params(
                p_values,
                lambda: _chunk(ids, start, n_valid, tables, samp,
                               flat_arenas))

    return chunk_pure


def _build_serving_fns(model, batch, max_cache_len,
                       cfg: GenerationConfig, steps_per_call, wq=None):
    """Pure (params, ...) -> (...) functions for prefill and one decode
    block; the exported/jitted serving programs.

    Three serving modes, all artifact-exportable (the reference's
    AnalysisPredictor serves the full decode configuration from the
    artifact alone — ``paddle/fluid/inference/api/analysis_predictor.h:94``):

    - greedy / sampled (``cfg.do_sample``): the prefill emits the first
      token and a threaded PRNG key; each block scans ``steps_per_call``
      decode steps, splitting the key per step.
    - beam (``cfg.num_beams > 1``): the prefill top-k-expands to
      ``[B*K]`` cache rows; each block scans the beam body and emits
      per-step (token, parent) pairs — the HOST accumulates them and
      backtraces once at the end (beam results are only final after the
      last step, so the block protocol ships the tree, not sequences).
    """
    n_layers, hkv, d = model.kv_cache_spec()
    cache_dtype = jnp.dtype(cfg.cache_dtype or cfg.compute_dtype)
    k = cfg.num_beams
    _with_params = _param_swapper(model, cfg, wq=wq)

    if k > 1:
        def prefill_pure(p_values, ids, lens):
            def run():
                kvs = init_kv_cache(n_layers, batch, max_cache_len, hkv,
                                    d, cache_dtype)
                logits, kvs = model.prefill(ids, lens, kvs)   # [B, V]
                lp0 = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
                top_lp, tok0 = jax.lax.top_k(lp0, k)          # [B, K]
                tok0 = tok0.astype(jnp.int32)
                done0 = (jnp.zeros((batch, k), bool)
                         if cfg.eos_token_id is None
                         else tok0 == cfg.eos_token_id)
                kvs = [(jnp.repeat(kc, k, axis=0),
                        jnp.repeat(vc, k, axis=0)) for kc, vc in kvs]
                lens_bk = jnp.repeat(lens, k, axis=0)
                blen0 = jnp.ones((batch, k), jnp.int32)
                return ((tok0, lens_bk, done0, top_lp, blen0)
                        + tuple(_flatten_kvs(kvs)))
            return _with_params(p_values, run)

        def block_pure(p_values, tok, lens, done, lp, blen, *flat_kvs):
            def run():
                kvs = _unflatten_kvs(list(flat_kvs))
                carry = (tok.reshape(-1), lens, kvs, lp, blen, done)
                (tok_f, lens_f, kvs_f, lp_f, blen_f, done_f), \
                    (toks, parents, lps, blens) = jax.lax.scan(
                        beam_scan_body(model, cfg, batch, k), carry,
                        None, length=steps_per_call)
                # toks/parents/lps/blens: [steps, B, K] — per-step scores
                # let the host truncate the tree mid-block and still pick
                # the best beam at exactly max_new_tokens
                return ((toks, parents, lps, blens,
                         tok_f.reshape(batch, k), lens_f, done_f, lp_f,
                         blen_f) + tuple(_flatten_kvs(kvs_f)))
            return _with_params(p_values, run)

        return prefill_pure, block_pure

    def prefill_pure(p_values, ids, lens, key):
        def run():
            kvs = init_kv_cache(n_layers, batch, max_cache_len, hkv, d,
                                cache_dtype)
            logits, kvs = model.prefill(ids, lens, kvs)
            if cfg.do_sample:
                key0, keyr = jax.random.split(key)
            else:
                key0 = keyr = key
            tok0 = sample_token(logits, key0, cfg)
            done0 = (jnp.zeros((batch,), bool)
                     if cfg.eos_token_id is None
                     else tok0 == cfg.eos_token_id)
            return (tok0, lens, done0, keyr) + tuple(_flatten_kvs(kvs))
        return _with_params(p_values, run)

    return prefill_pure, _build_decode_block(model, cfg, steps_per_call,
                                             wq=wq)


class LLMPredictor:
    """Cached-KV generative serving session (see module docstring).

    Shapes are static per predictor: ``batch`` sequences, right-padded
    prompts of ``prompt_len``, cache capacity ``max_cache_len``.
    ``start()`` prefills and returns the first generated token;
    ``decode(n)`` continues n more tokens; ``generate()`` is both.
    """

    def __init__(self, model=None, *, batch, prompt_len,
                 max_cache_len=None, steps_per_call=16,
                 eos_token_id=None, pad_token_id=0,
                 do_sample=False, temperature=1.0, top_k=0, top_p=1.0,
                 num_beams=1, length_penalty=0.0,
                 compute_dtype="bfloat16", cache_dtype=None,
                 weight_dtype=None, _loaded=None):
        self.batch = int(batch)
        self.prompt_len = int(prompt_len)
        self.max_cache_len = int(max_cache_len or (prompt_len + 256))
        self.steps_per_call = int(steps_per_call)
        if self.max_cache_len < self.prompt_len + 1:
            raise ValueError(
                f"max_cache_len ({self.max_cache_len}) must be >= "
                f"prompt_len + 1 ({self.prompt_len + 1}) — the cache "
                "holds the prompt plus at least the first generated "
                "token's K/V")
        if num_beams > 1 and do_sample:
            raise ValueError("num_beams > 1 with do_sample=True is not "
                             "supported (beam search scores greedily)")
        self.cfg = GenerationConfig(
            do_sample=bool(do_sample), temperature=float(temperature),
            top_k=int(top_k), top_p=float(top_p),
            num_beams=int(num_beams),
            length_penalty=float(length_penalty),
            eos_token_id=eos_token_id, pad_token_id=int(pad_token_id),
            compute_dtype=str(compute_dtype),
            cache_dtype=None if cache_dtype is None else str(cache_dtype))
        self._state = None       # (tok, lens, done, flat_kvs)
        self._written = 0        # python-side high-water mark
        # a block emits steps_per_call tokens; tokens beyond what the
        # caller asked for are buffered here and drained first on the
        # next decode() (the device carry is always block-aligned)
        self._pending: Optional[np.ndarray] = None
        self.weight_dtype = normalize_weight_dtype(weight_dtype)
        self._wq = None
        if _loaded is not None:
            if self.weight_dtype is not None:
                raise ValueError(
                    "weight_dtype is a load-time quantization of the "
                    "in-process model; a deserialized artifact carries "
                    "its weights baked into the exported programs")
            (self._prefill, self._block, self._param_values) = _loaded
            self._model = None
            return
        if model is None:
            raise ValueError("LLMPredictor needs a model (or .load(path))")
        self._model = model
        model.eval()
        if self.weight_dtype is not None:
            self._wq = build_weight_quant_plan(model, self.weight_dtype)
        prefill, block = _build_serving_fns(
            model, self.batch, self.max_cache_len, self.cfg,
            self.steps_per_call, wq=self._wq)
        self._prefill = jax.jit(prefill)
        self._block = jax.jit(block)
        params, buffers = model_arrays(model)
        if self._wq is not None:
            self._param_values = self._wq.placeholder_params(params) + \
                [bf._value for bf in buffers] + self._wq.flat_values()
        else:
            self._param_values = [p._value for p in params] + \
                [bf._value for bf in buffers]

    # -- session --
    def _check_prompt(self, input_ids, seq_lens):
        ids = np.asarray(getattr(input_ids, "_value", input_ids))
        if ids.shape != (self.batch, self.prompt_len):
            raise ValueError(
                f"prompt must be [{self.batch}, {self.prompt_len}], got "
                f"{list(ids.shape)}")
        lens = (np.full((self.batch,), self.prompt_len, np.int32)
                if seq_lens is None
                else np.asarray(getattr(seq_lens, "_value", seq_lens)))
        if lens.shape != (self.batch,) or (lens < 1).any() or \
                (lens > self.prompt_len).any():
            # jit-side gathers clamp out-of-range indices silently, which
            # would decode plausible-but-wrong tokens — fail loudly here
            raise ValueError(
                f"seq_lens must be [{self.batch}] ints in "
                f"[1, {self.prompt_len}], got {lens.tolist()}")
        return ids, lens

    def start(self, input_ids, seq_lens=None, seed: int = 0) -> np.ndarray:
        """Prefill the prompt; returns the first generated token [B]
        (greedy/sampled) or the initial beams [B, K] (beam mode)."""
        ids, lens = self._check_prompt(input_ids, seq_lens)
        if self.cfg.num_beams > 1:
            out = self._prefill(self._param_values,
                                jnp.asarray(ids, jnp.int32),
                                jnp.asarray(lens, jnp.int32))
            tok0, lens_bk, done, lp, blen = out[:5]
            self._state = (tok0, lens_bk, done, lp, blen, list(out[5:]))
            # host-side beam tree: ids/parents/scores [T, B, K]
            k = self.cfg.num_beams
            self._tree_ids = [np.asarray(tok0)[None]]
            self._tree_parents = [np.tile(
                np.arange(k, dtype=np.int32)[None, None],
                (1, self.batch, 1))]
            self._tree_lp = [np.asarray(lp)[None]]
            self._tree_blen = [np.asarray(blen)[None]]
        else:
            key = jnp.asarray(
                np.asarray(jax.random.PRNGKey(seed), np.uint32))
            out = self._prefill(self._param_values,
                                jnp.asarray(ids, jnp.int32),
                                jnp.asarray(lens, jnp.int32), key)
            tok0, lens_d, done, key = out[0], out[1], out[2], out[3]
            self._state = (tok0, lens_d, done, key, list(out[4:]))
        self._written = int(lens.max()) + 1
        self._pending = None
        return np.asarray(out[0])

    def _run_block(self):
        if self.cfg.num_beams > 1:
            tok, lens, done, lp, blen, flat = self._state
            out = self._block(self._param_values, tok, lens, done, lp,
                              blen, *flat)
            toks, parents = np.asarray(out[0]), np.asarray(out[1])
            self._tree_lp.append(np.asarray(out[2]))
            self._tree_blen.append(np.asarray(out[3]))
            self._state = (out[4], out[5], out[6], out[7], out[8],
                           list(out[9:]))
            self._tree_ids.append(toks)
            self._tree_parents.append(parents)
            return None  # beam tokens are final only after backtrace
        tok, lens, done, key, flat = self._state
        out = self._block(self._param_values, tok, lens, done, key, *flat)
        toks = np.asarray(out[0])
        self._state = (out[1], out[2], out[3], out[4], list(out[5:]))
        return toks

    def decode(self, n: int) -> np.ndarray:
        """Decode ``n`` more tokens; returns [B, n] int32.  Beam mode
        has no incremental token stream (beams reorder retroactively):
        use ``generate()``."""
        if self.cfg.num_beams > 1:
            raise RuntimeError(
                "decode() is not available with num_beams > 1 — beam "
                "tokens are only final after the last step's backtrace; "
                "use generate(), which returns the best sequences")
        if self._state is None:
            raise RuntimeError("call start() before decode()")
        if n <= 0:
            return np.zeros((self.batch, 0), np.int32)
        buffered = 0 if self._pending is None else self._pending.shape[1]
        need_blocks = max(0, -(-(n - buffered) // self.steps_per_call))
        if self._written + need_blocks * self.steps_per_call \
                > self.max_cache_len + 1:
            raise ValueError(
                f"decoding {n} more tokens exceeds max_cache_len "
                f"({self.max_cache_len}); session has written "
                f"{self._written}")
        chunks: List[np.ndarray] = ([] if self._pending is None
                                    else [self._pending])
        for _ in range(need_blocks):
            chunks.append(self._run_block())
            self._written += self.steps_per_call
        all_toks = np.concatenate(chunks, axis=1)
        self._pending = all_toks[:, n:] if all_toks.shape[1] > n else None
        return all_toks[:, :n]

    def generate(self, input_ids, seq_lens=None,
                 max_new_tokens: int = 32, seed: int = 0) -> np.ndarray:
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        first = self.start(input_ids, seq_lens, seed=seed)
        if self.cfg.num_beams > 1:
            n_blocks = -(-(max_new_tokens - 1) // self.steps_per_call)
            if self._written + n_blocks * self.steps_per_call \
                    > self.max_cache_len + 1:
                raise ValueError(
                    f"decoding {max_new_tokens} tokens exceeds "
                    f"max_cache_len ({self.max_cache_len})")
            for _ in range(n_blocks):
                self._run_block()
                self._written += self.steps_per_call
            return self._finalize_beams(max_new_tokens)
        if max_new_tokens == 1:
            return first[:, None]
        rest = self.decode(max_new_tokens - 1)
        return np.concatenate([first[:, None], rest], axis=1)

    def _finalize_beams(self, max_new_tokens: int) -> np.ndarray:
        """Backtrace the accumulated (token, parent) tree and return the
        best beam per batch row under the length penalty."""
        ids = jnp.asarray(
            np.concatenate(self._tree_ids, axis=0)[:max_new_tokens])
        parents = jnp.asarray(
            np.concatenate(self._tree_parents, axis=0)[:max_new_tokens])
        seqs = np.asarray(_gather_tree_arrays(ids, parents))  # [T, B, K]
        # scores AT step T (not at the block boundary the scan ran to)
        lp = np.concatenate(self._tree_lp, axis=0)[max_new_tokens - 1]
        blen = np.concatenate(self._tree_blen,
                              axis=0)[max_new_tokens - 1].astype(
                                  np.float32)
        if self.cfg.length_penalty:
            scores = lp / (blen ** self.cfg.length_penalty)
        else:
            scores = lp
        best = scores.argmax(-1)                              # [B]
        return np.swapaxes(seqs, 0, 1)[
            np.arange(self.batch), :, best].astype(np.int32)

    # -- artifact --
    def save(self, path: str):
        """Export prefill + decode-block as portable StableHLO plus a
        weights pickle (one ``.ptpu_llm`` file).  The FULL decode
        configuration — greedy, sampled (temperature/top-k, PRNG key
        threaded through the artifact), or beam (num_beams, length
        penalty) — is baked into the exported programs, so a loaded
        artifact serves it without the model class (the reference's
        AnalysisPredictor deployment contract)."""
        if self._model is None:
            raise RuntimeError("save() needs the in-process model")
        if self._wq is not None:
            raise NotImplementedError(
                "save() with weight_dtype='int8'/'int4' is not wired — "
                "the exported artifact's weights pickle would carry the "
                "code/scale planes without the loader knowing the plan "
                "layout; quantized-weight predictors serve in-process")
        from jax import export as jax_export
        prefill, block = _build_serving_fns(
            self._model, self.batch, self.max_cache_len, self.cfg,
            self.steps_per_call)
        p_shapes = [jax.ShapeDtypeStruct(v.shape, v.dtype)
                    for v in self._param_values]
        b = self.batch
        k = self.cfg.num_beams
        ids_s = jax.ShapeDtypeStruct((b, self.prompt_len), jnp.int32)
        i32 = jax.ShapeDtypeStruct((b,), jnp.int32)
        booln = jax.ShapeDtypeStruct((b,), jnp.bool_)
        key_s = jax.ShapeDtypeStruct((2,), jnp.uint32)
        n_layers, hkv, d = self._model.kv_cache_spec()
        cache_dtype = jnp.dtype(self.cfg.cache_dtype
                                or self.cfg.compute_dtype)
        cache_rows = b * k
        from ..ops.pallas.decode_attention import cache_shape
        kv_s = [jax.ShapeDtypeStruct(
            cache_shape(cache_rows, hkv, self.max_cache_len, d),
            cache_dtype)
            for _ in range(2 * n_layers)]

        def _export(fn, *shapes):
            jitted = jax.jit(fn)
            try:
                return jax_export.export(
                    jitted, platforms=("cpu", "tpu"))(*shapes).serialize()
            except TypeError:
                # only an older jax lacking the platforms kwarg falls back
                # (single-platform artifact); real export errors propagate
                return jax_export.export(jitted)(*shapes).serialize()

        if k > 1:
            bk_i32 = jax.ShapeDtypeStruct((b, k), jnp.int32)
            bk_f32 = jax.ShapeDtypeStruct((b, k), jnp.float32)
            bk_bool = jax.ShapeDtypeStruct((b, k), jnp.bool_)
            rows_i32 = jax.ShapeDtypeStruct((cache_rows,), jnp.int32)
            pre_blob = _export(prefill, p_shapes, ids_s, i32)
            blk_blob = _export(block, p_shapes, bk_i32, rows_i32,
                               bk_bool, bk_f32, bk_i32, *kv_s)
        else:
            pre_blob = _export(prefill, p_shapes, ids_s, i32, key_s)
            blk_blob = _export(block, p_shapes, i32, i32, booln, key_s,
                               *kv_s)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path + ".ptpu_llm", "wb") as f:
            pickle.dump({
                "version": 2,  # v2: PRNG key threaded / beam planes
                "prefill": pre_blob, "block": blk_blob,
                "values": [np.asarray(v) for v in self._param_values],
                "meta": {
                    "batch": self.batch, "prompt_len": self.prompt_len,
                    "max_cache_len": self.max_cache_len,
                    "steps_per_call": self.steps_per_call,
                    "eos_token_id": self.cfg.eos_token_id,
                    "pad_token_id": self.cfg.pad_token_id,
                    "do_sample": self.cfg.do_sample,
                    "temperature": self.cfg.temperature,
                    "top_k": self.cfg.top_k,
                    "top_p": self.cfg.top_p,
                    "num_beams": self.cfg.num_beams,
                    "length_penalty": self.cfg.length_penalty,
                    "compute_dtype": self.cfg.compute_dtype,
                    "cache_dtype": self.cfg.cache_dtype,
                }}, f)

    @classmethod
    def load(cls, path: str) -> "LLMPredictor":
        """Rebuild a serving session from a ``.ptpu_llm`` artifact —
        no model class needed (the Predictor deployment path)."""
        from jax import export as jax_export
        with open(path + ".ptpu_llm", "rb") as f:
            blob = pickle.load(f)
        if blob.get("version", 1) < 2:
            raise ValueError(
                "this .ptpu_llm artifact was saved by an older "
                "LLMPredictor whose serving programs lack the threaded "
                "PRNG key / beam planes — re-export it with save() "
                "(the block call protocol changed; a silent load would "
                "mis-slice the block outputs)")
        meta = blob["meta"]
        pre = jax_export.deserialize(blob["prefill"])
        blk = jax_export.deserialize(blob["block"])
        values = [jnp.asarray(v) for v in blob["values"]]
        return cls(
            batch=meta["batch"], prompt_len=meta["prompt_len"],
            max_cache_len=meta["max_cache_len"],
            steps_per_call=meta["steps_per_call"],
            eos_token_id=meta["eos_token_id"],
            pad_token_id=meta["pad_token_id"],
            do_sample=meta.get("do_sample", False),
            temperature=meta.get("temperature", 1.0),
            top_k=meta.get("top_k", 0),
            top_p=meta.get("top_p", 1.0),
            num_beams=meta.get("num_beams", 1),
            length_penalty=meta.get("length_penalty", 0.0),
            compute_dtype=meta["compute_dtype"],
            cache_dtype=meta["cache_dtype"],
            _loaded=(lambda pv, *a: pre.call(pv, *a),
                     lambda pv, *a: blk.call(pv, *a),
                     values))
