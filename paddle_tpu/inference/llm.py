"""LLM serving: a KV-cache decode session for the Predictor stack.

Reference analogue: the fused decode-serving path —
``paddle/fluid/operators/fused/fused_multi_transformer_op.cu`` (+ its
int8 twin) driven step-by-step under AnalysisPredictor with persistent
cache tensors.  TPU formulation:

- ``LLMPredictor`` owns the session state (token, lengths, done flags,
  per-layer KV buffers) as device arrays between calls — the session is
  the cache's lifetime, like the reference's cache_kv variables living
  in the predictor scope.
- Decode runs in BLOCKS of ``steps_per_call`` tokens: one compiled call
  (``lax.scan`` inside) emits K tokens, so the per-dispatch cost
  (~6-10 ms through the axon tunnel) amortizes over K steps while the
  session stays incremental.  The float->compute-dtype weight cast also
  amortizes per block.
- ``save()`` exports the prefill and decode-block programs as portable
  StableHLO (jax.export, same mechanism as ``paddle.jit.save``) plus a
  weights pickle; ``LLMPredictor.load()`` rebuilds the session without
  the model's Python class.  Serving artifacts decode greedily —
  deterministic tokens for a given prompt.
"""

from __future__ import annotations

import os
import pickle
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.generation import (GenerationConfig, decode_scan_body,
                                 init_kv_cache, model_arrays, swap_call)


def _flatten_kvs(kvs):
    flat = []
    for k, v in kvs:
        flat.append(k)
        flat.append(v)
    return flat


def _unflatten_kvs(flat):
    return [(flat[i], flat[i + 1]) for i in range(0, len(flat), 2)]


def _build_serving_fns(model, batch, max_cache_len,
                       cfg: GenerationConfig, steps_per_call):
    """Pure (params, ...) -> (...) functions for prefill and one decode
    block; the exported/jitted serving programs."""
    params, buffers = model_arrays(model)
    n_layers, hkv, d = model.kv_cache_spec()
    cache_dtype = jnp.dtype(cfg.cache_dtype or cfg.compute_dtype)

    def _with_params(pb_values, fn):
        p_values = pb_values[:len(params)]
        b_values = pb_values[len(params):]
        return swap_call(params, buffers, p_values, b_values,
                         cfg.compute_dtype, fn)

    def prefill_pure(p_values, ids, lens):
        def run():
            kvs = init_kv_cache(n_layers, batch, max_cache_len, hkv, d,
                                cache_dtype)
            logits, kvs = model.prefill(ids, lens, kvs)
            tok0 = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            done0 = (jnp.zeros((batch,), bool)
                     if cfg.eos_token_id is None
                     else tok0 == cfg.eos_token_id)
            return (tok0, lens, done0) + tuple(_flatten_kvs(kvs))
        return _with_params(p_values, run)

    def block_pure(p_values, tok, lens, done, *flat_kvs):
        def run():
            kvs = _unflatten_kvs(list(flat_kvs))
            key = jax.random.PRNGKey(0)  # unused: serving cfg is greedy
            (tok_f, lens_f, kvs, _, done_f), toks = jax.lax.scan(
                decode_scan_body(model, cfg), (tok, lens, kvs, key, done),
                None, length=steps_per_call)
            return ((toks.T.astype(jnp.int32), tok_f, lens_f, done_f)
                    + tuple(_flatten_kvs(kvs)))
        return _with_params(p_values, run)

    return prefill_pure, block_pure


class LLMPredictor:
    """Cached-KV generative serving session (see module docstring).

    Shapes are static per predictor: ``batch`` sequences, right-padded
    prompts of ``prompt_len``, cache capacity ``max_cache_len``.
    ``start()`` prefills and returns the first generated token;
    ``decode(n)`` continues n more tokens; ``generate()`` is both.
    """

    def __init__(self, model=None, *, batch, prompt_len,
                 max_cache_len=None, steps_per_call=16,
                 eos_token_id=None, pad_token_id=0,
                 compute_dtype="bfloat16", cache_dtype=None,
                 _loaded=None):
        self.batch = int(batch)
        self.prompt_len = int(prompt_len)
        self.max_cache_len = int(max_cache_len or (prompt_len + 256))
        self.steps_per_call = int(steps_per_call)
        if self.max_cache_len < self.prompt_len + 1:
            raise ValueError(
                f"max_cache_len ({self.max_cache_len}) must be >= "
                f"prompt_len + 1 ({self.prompt_len + 1}) — the cache "
                "holds the prompt plus at least the first generated "
                "token's K/V")
        self.cfg = GenerationConfig(
            eos_token_id=eos_token_id, pad_token_id=int(pad_token_id),
            compute_dtype=str(compute_dtype),
            cache_dtype=None if cache_dtype is None else str(cache_dtype))
        self._state = None       # (tok, lens, done, flat_kvs)
        self._written = 0        # python-side high-water mark
        # a block emits steps_per_call tokens; tokens beyond what the
        # caller asked for are buffered here and drained first on the
        # next decode() (the device carry is always block-aligned)
        self._pending: Optional[np.ndarray] = None
        if _loaded is not None:
            (self._prefill, self._block, self._param_values) = _loaded
            self._model = None
            return
        if model is None:
            raise ValueError("LLMPredictor needs a model (or .load(path))")
        self._model = model
        model.eval()
        prefill, block = _build_serving_fns(
            model, self.batch, self.max_cache_len, self.cfg,
            self.steps_per_call)
        self._prefill = jax.jit(prefill)
        self._block = jax.jit(block)
        params, buffers = model_arrays(model)
        self._param_values = [p._value for p in params] + \
            [bf._value for bf in buffers]

    # -- session --
    def start(self, input_ids, seq_lens=None) -> np.ndarray:
        """Prefill the prompt; returns the first generated token [B]."""
        ids = np.asarray(getattr(input_ids, "_value", input_ids))
        if ids.shape != (self.batch, self.prompt_len):
            raise ValueError(
                f"prompt must be [{self.batch}, {self.prompt_len}], got "
                f"{list(ids.shape)}")
        lens = (np.full((self.batch,), self.prompt_len, np.int32)
                if seq_lens is None
                else np.asarray(getattr(seq_lens, "_value", seq_lens)))
        if lens.shape != (self.batch,) or (lens < 1).any() or \
                (lens > self.prompt_len).any():
            # jit-side gathers clamp out-of-range indices silently, which
            # would decode plausible-but-wrong tokens — fail loudly here
            raise ValueError(
                f"seq_lens must be [{self.batch}] ints in "
                f"[1, {self.prompt_len}], got {lens.tolist()}")
        out = self._prefill(self._param_values,
                            jnp.asarray(ids, jnp.int32),
                            jnp.asarray(lens, jnp.int32))
        tok0, lens_d, done = out[0], out[1], out[2]
        self._state = (tok0, lens_d, done, list(out[3:]))
        self._written = int(lens.max()) + 1
        self._pending = None
        return np.asarray(tok0)

    def decode(self, n: int) -> np.ndarray:
        """Decode ``n`` more tokens; returns [B, n] int32."""
        if self._state is None:
            raise RuntimeError("call start() before decode()")
        if n <= 0:
            return np.zeros((self.batch, 0), np.int32)
        buffered = 0 if self._pending is None else self._pending.shape[1]
        need_blocks = max(0, -(-(n - buffered) // self.steps_per_call))
        if self._written + need_blocks * self.steps_per_call \
                > self.max_cache_len + 1:
            raise ValueError(
                f"decoding {n} more tokens exceeds max_cache_len "
                f"({self.max_cache_len}); session has written "
                f"{self._written}")
        tok, lens, done, flat = self._state
        chunks: List[np.ndarray] = ([] if self._pending is None
                                    else [self._pending])
        for _ in range(need_blocks):
            out = self._block(self._param_values, tok, lens, done, *flat)
            toks, tok, lens, done = out[0], out[1], out[2], out[3]
            flat = list(out[4:])
            chunks.append(np.asarray(toks))
            self._written += self.steps_per_call
        self._state = (tok, lens, done, flat)
        all_toks = np.concatenate(chunks, axis=1)
        self._pending = all_toks[:, n:] if all_toks.shape[1] > n else None
        return all_toks[:, :n]

    def generate(self, input_ids, seq_lens=None,
                 max_new_tokens: int = 32) -> np.ndarray:
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        first = self.start(input_ids, seq_lens)
        if max_new_tokens == 1:
            return first[:, None]
        rest = self.decode(max_new_tokens - 1)
        return np.concatenate([first[:, None], rest], axis=1)

    # -- artifact --
    def save(self, path: str):
        """Export prefill + decode-block as portable StableHLO plus a
        weights pickle (one ``.ptpu_llm`` file)."""
        if self._model is None:
            raise RuntimeError("save() needs the in-process model")
        from jax import export as jax_export
        prefill, block = _build_serving_fns(
            self._model, self.batch, self.max_cache_len, self.cfg,
            self.steps_per_call)
        p_shapes = [jax.ShapeDtypeStruct(v.shape, v.dtype)
                    for v in self._param_values]
        b = self.batch
        ids_s = jax.ShapeDtypeStruct((b, self.prompt_len), jnp.int32)
        i32 = jax.ShapeDtypeStruct((b,), jnp.int32)
        booln = jax.ShapeDtypeStruct((b,), jnp.bool_)
        n_layers, hkv, d = self._model.kv_cache_spec()
        cache_dtype = jnp.dtype(self.cfg.cache_dtype
                                or self.cfg.compute_dtype)
        kv_s = [jax.ShapeDtypeStruct(
            (b, self.max_cache_len, hkv, d), cache_dtype)
            for _ in range(2 * n_layers)]

        def _export(fn, *shapes):
            jitted = jax.jit(fn)
            try:
                return jax_export.export(
                    jitted, platforms=("cpu", "tpu"))(*shapes).serialize()
            except TypeError:
                # only an older jax lacking the platforms kwarg falls back
                # (single-platform artifact); real export errors propagate
                return jax_export.export(jitted)(*shapes).serialize()

        pre_blob = _export(prefill, p_shapes, ids_s, i32)
        blk_blob = _export(block, p_shapes, i32, i32, booln, *kv_s)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path + ".ptpu_llm", "wb") as f:
            pickle.dump({
                "prefill": pre_blob, "block": blk_blob,
                "values": [np.asarray(v) for v in self._param_values],
                "meta": {
                    "batch": self.batch, "prompt_len": self.prompt_len,
                    "max_cache_len": self.max_cache_len,
                    "steps_per_call": self.steps_per_call,
                    "eos_token_id": self.cfg.eos_token_id,
                    "pad_token_id": self.cfg.pad_token_id,
                    "compute_dtype": self.cfg.compute_dtype,
                    "cache_dtype": self.cfg.cache_dtype,
                }}, f)

    @classmethod
    def load(cls, path: str) -> "LLMPredictor":
        """Rebuild a serving session from a ``.ptpu_llm`` artifact —
        no model class needed (the Predictor deployment path)."""
        from jax import export as jax_export
        with open(path + ".ptpu_llm", "rb") as f:
            blob = pickle.load(f)
        meta = blob["meta"]
        pre = jax_export.deserialize(blob["prefill"])
        blk = jax_export.deserialize(blob["block"])
        values = [jnp.asarray(v) for v in blob["values"]]
        return cls(
            batch=meta["batch"], prompt_len=meta["prompt_len"],
            max_cache_len=meta["max_cache_len"],
            steps_per_call=meta["steps_per_call"],
            eos_token_id=meta["eos_token_id"],
            pad_token_id=meta["pad_token_id"],
            compute_dtype=meta["compute_dtype"],
            cache_dtype=meta["cache_dtype"],
            _loaded=(lambda pv, ids, lens: pre.call(pv, ids, lens),
                     lambda pv, *a: blk.call(pv, *a),
                     values))
