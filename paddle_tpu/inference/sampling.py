"""Per-request sampling & constrained decoding for the serving engine.

Everything the engine emitted since PR 1 was greedy argmax — one
scenario.  This module makes generation config first-class API surface
(the reference framework's GenerationConfig role, per REQUEST instead
of per engine): a ``SamplingParams`` record carried by ``submit()``,
a slot-indexed PRNG plane, a batched per-row sampler applied inside
the traced decode/chunk-final/verify dispatches, and a host-side
logit-processor chain (repetition penalty + token-mask constrained
decoding, the Outlines approach).

Design decisions, in order of load-bearing-ness:

- **Position-keyed per-request PRNG.**  Each request carries its own
  base key ``PRNGKey(seed)``; the key for its i-th OUTPUT token is
  ``fold_in(fold_in(base, i), lane)`` (lane 0 = the accept-test
  uniform of speculative sampling, lane 1 = the categorical draw).
  Every random draw is therefore a pure function of
  ``(seed, output position, lane)`` — slot reuse, batch composition,
  prefix-cache hits, chunked-prefill layout and engine restarts cannot
  change a request's stream, and speculative ROLLBACK rewinds the
  stream for free: the engine re-derives positions from host truth
  (``len(req.tokens)``) each dispatch, so a rejected draft's positions
  are simply drawn again next forward (their earlier draws were never
  consumed — acceptance stopped before them — so independence holds).
- **Per-row planes, not per-program configs.**  Temperature / top-k /
  top-p / repetition penalty / greedy-ness ride as ``[B]`` vectors
  ("planes") into ONE compiled program per (steps, feature-flags)
  bucket: a greedy row and three differently-sampled rows share the
  dispatch, mixed freely, exactly like ``lens``/``done`` already mix
  fill levels.  Greedy rows select ``argmax`` through a per-row
  ``is_greedy`` mask, so the default path stays BIT-EXACT (argmax of
  the f32-cast logits equals argmax of the raw logits — the cast is
  monotone and exact).
- **Feature flags are static, planes are data.**  The per-row
  categorical, the top-k/top-p sort-filter (a full-vocab sort — pure-
  temperature mixes skip it), the repetition-penalty presence plane
  ([B, V] bool) and the constrained-mask bias plane ([B, V] f32) each
  cost real compute/transfer, so each is compiled in only when a
  dispatch's active mix needs it
  (``flags = (sampled, filtered, penalty, bias)``); an all-greedy
  engine runs the exact pre-sampling program shape forever.
- **Logit-processor chain order**: repetition penalty (CTRL-style:
  divide positive / multiply negative logits of context tokens), then
  the token-mask bias (0 allowed / -1e9 banned), then temperature,
  top-k, top-p.  The penalty's presence set is updated IN-TRACE as a
  multi-step decode block emits tokens (one-hot OR into the carried
  plane), so penalty rows ride full blocks; mask rows cannot — their
  host-side state machine must observe each token — so the engine
  clamps their blocks to single steps.

``DfaTokenMask`` is the reference mask processor: a dense
``[states, vocab]`` transition table (entries < 0 = banned) drives
token-mask constrained decoding for any regular language (JSON
skeletons, regexes compiled elsewhere) — the same mechanism structured
-output systems use, small enough to audit.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

# the per-row top-k/top-p filter is owned by models/generation.py (ONE
# implementation of the nucleus prefix/tie rule for both the
# whole-batch ``sample_token`` config and these per-request planes);
# re-exported here as part of this module's documented surface
from ..models.generation import filter_top_k_top_p  # noqa: F401

# temperatures below this sample like argmax anyway; treat them AS
# argmax so the scale 1/T never overflows inside the traced program
TEMP_EPS = 1e-4

# additive bias of banned tokens: finite (an -inf bias would turn an
# all-banned row into a NaN softmax; -1e9 keeps the math defined and
# is unreachable by any real logit)
MASK_BIAS = -1e9


class TokenMaskProcessor:
    """Host-side state machine driving token-mask constrained decoding.

    The engine calls ``begin(prompt_ids)`` once at submit, ``allowed()``
    before every decode dispatch of the request (a ``[vocab]`` bool
    vector of legal next tokens, turned into the traced bias plane),
    and ``advance(token)`` after each emitted token.  State is PER
    REQUEST — give each request its own processor instance.

    Masks compose with temperature/top-k/top-p sampling and with greedy
    decoding; they do NOT compose with speculative decoding (a draft
    position's mask depends on host state the drafter bypasses — the
    engine rejects that combination at submit).

    An ``allowed()`` with NO legal token ("dead end") means the grammar
    is complete: the engine finishes the request there, exactly like an
    EOS (an all-banned state cannot constrain — its bias plane is a
    uniform shift — so it is the natural encoding of an accept state in
    a DFA that does not map EOS).  A dead START state is rejected at
    submit."""

    def begin(self, prompt_ids: np.ndarray) -> None:
        raise NotImplementedError

    def allowed(self) -> np.ndarray:
        raise NotImplementedError

    def advance(self, token: int) -> None:
        raise NotImplementedError


class DfaTokenMask(TokenMaskProcessor):
    """Constrained decoding over a dense DFA transition table.

    ``table`` is ``[n_states, vocab]`` int32: entry ``(s, t)`` is the
    state after emitting token ``t`` in state ``s``, or ``-1`` when
    ``t`` is illegal there.  ``allowed()`` is one table-row compare;
    ``advance`` one lookup.  Anything regular (toy JSON grammars,
    compiled regexes) lowers to this form; the prompt does not move the
    DFA (constrained decoding constrains the OUTPUT)."""

    def __init__(self, table, start_state: int = 0):
        self.table = np.asarray(table, np.int32)
        if self.table.ndim != 2:
            raise ValueError(
                f"DFA table must be [n_states, vocab], got "
                f"{list(self.table.shape)}")
        self.start_state = int(start_state)
        if not 0 <= self.start_state < self.table.shape[0]:
            raise ValueError(
                f"start_state {start_state} outside the "
                f"{self.table.shape[0]}-state table")
        self.state = self.start_state

    def begin(self, prompt_ids: np.ndarray) -> None:
        self.state = self.start_state

    def allowed(self) -> np.ndarray:
        return self.table[self.state] >= 0

    def advance(self, token: int) -> None:
        nxt = int(self.table[self.state, int(token)])
        if nxt < 0:
            raise RuntimeError(
                f"token {token} is illegal in DFA state {self.state} — "
                f"the mask bias should have made this unreachable")
        self.state = nxt


@dataclass(frozen=True)
class SamplingParams:
    """Per-request decode configuration, carried by
    ``ServingEngine.submit(sampling=...)``.

    ``temperature=0`` (or ``top_k=1``) degenerates to greedy argmax —
    the engine routes such rows through the bit-exact greedy path.
    ``seed`` names the request's PRNG stream (see the module docstring
    for the position-keyed derivation); the default ``None`` derives a
    DISTINCT stream per request (engine seed folded with the request
    id — concurrent no-seed requests differ from each other, and a
    replayed submission order reproduces), so best-of-n submissions
    are diverse without hand-assigned seeds.  ``mask_processor`` plugs
    a host-side :class:`TokenMaskProcessor`; it is stateful and must
    not be shared between requests."""

    temperature: float = 1.0
    top_k: int = 0                    # 0 = full vocabulary
    top_p: float = 1.0                # 1.0 = off
    repetition_penalty: float = 1.0   # 1.0 = off
    seed: Optional[int] = None        # None = per-request stream
    mask_processor: Optional[TokenMaskProcessor] = field(default=None)

    @property
    def is_greedy(self) -> bool:
        """Argmax instead of a categorical draw.  Processors (penalty,
        mask) still apply — greedy-over-masked-logits is a valid
        constrained mode; only DEFAULT params (no processors) promise
        bit-exactness with the pre-sampling greedy engine."""
        return self.temperature <= TEMP_EPS or self.top_k == 1

    @property
    def needs_penalty(self) -> bool:
        return self.repetition_penalty != 1.0

    def validate(self):
        if self.temperature < 0.0:
            raise ValueError(
                f"temperature must be >= 0, got {self.temperature}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(
                f"top_p must be in (0, 1], got {self.top_p}")
        if self.repetition_penalty <= 0.0:
            raise ValueError(
                f"repetition_penalty must be > 0, got "
                f"{self.repetition_penalty}")
        if self.mask_processor is not None and \
                not isinstance(self.mask_processor, TokenMaskProcessor):
            raise ValueError(
                "mask_processor must be a TokenMaskProcessor")
        return self


GREEDY = SamplingParams(temperature=0.0)


def flags_of(params_list) -> tuple:
    """The static feature-flag bucket of a dispatch's active mix:
    ``(sampled, filtered, penalty, bias)``.  Determines both which
    planes ride in ``samp`` and which program variant compiles — same
    flags, same pytree structure, same executable.  ``filtered`` is
    the top-k/top-p sort-filter: a pure-temperature mix leaves it out
    and skips the full-vocab sort entirely."""
    ps = [p for p in params_list if p is not None]
    return (any(not p.is_greedy for p in ps),
            any(not p.is_greedy and (p.top_k > 0 or p.top_p < 1.0)
                for p in ps),
            any(p.needs_penalty for p in ps),
            any(p.mask_processor is not None for p in ps))


def row_planes(params: Optional[SamplingParams]):
    """One row's plane values ``(temp, top_k, top_p, greedy)``.
    Greedy rows get NEUTRAL filter values (temp 1, no top-k/p): the
    sampled branch's math then stays finite for them even though the
    ``greedy`` mask discards its result.  The repetition penalty is
    NOT part of the tuple — the ``rep``/``presence`` planes are built
    by the penalty branch of the engine's plane builder, the one
    source of that value."""
    p = params or GREEDY
    if p.is_greedy:
        return (1.0, 0, 1.0, True)
    return (max(float(p.temperature), TEMP_EPS), int(p.top_k),
            float(p.top_p), False)


def base_key(seed: int) -> np.ndarray:
    """The request's raw uint32 base key."""
    return np.asarray(jax.random.PRNGKey(int(seed)), np.uint32)


# -- traced helpers (inside the compiled serving programs) --

def _fold_keys(base, pos, lane):
    """Per-row key for output position ``pos[b]``, lane 0 (accept-test
    uniform) or 1 (categorical draw).  base: [B, 2] uint32; pos: [B]."""
    def one(k, p):
        return jax.random.fold_in(jax.random.fold_in(k, p), lane)
    return jax.vmap(one)(base, pos)


def process_logits(logits, samp, flags, presence=None):
    """The logit-processor chain BEFORE temperature: f32 cast,
    repetition penalty over the ``presence`` plane, constrained-mask
    bias.  Row-local and monotone-for-default-rows: a row with
    ``rep == 1`` and zero bias leaves with its logits' exact f32 cast,
    so its argmax is bit-identical to the raw argmax."""
    _sampled, _filtered, penalty, bias = flags
    lg = logits.astype(jnp.float32)
    if penalty:
        rep = samp["rep"]
        rep = rep.reshape(rep.shape + (1,) * (lg.ndim - rep.ndim))
        pen = jnp.where(lg > 0, lg / rep, lg * rep)
        lg = jnp.where(presence, pen, lg)
    if bias:
        lg = lg + samp["bias"]
    return lg




def categorical_rows(lg, keys):
    """Per-row categorical over [..., V] logits with per-row keys
    ([..., 2] uint32).  vmapped ``jax.random.categorical``, so each
    row's draw depends only on its own key + logits — the
    batch-composition-independence the seeded-determinism contract
    needs."""
    shape = lg.shape[:-1]
    flat = jax.vmap(jax.random.categorical)(
        keys.reshape(-1, 2), lg.reshape(-1, lg.shape[-1]))
    return flat.reshape(shape).astype(jnp.int32)


def sample_rows(logits, samp, flags, presence=None):
    """The full per-row chain of one decode position: process ->
    greedy argmax AND (when the ``sampled`` flag is compiled in)
    temperature/top-k/top-p categorical, selected per row by the
    ``greedy`` plane.  logits [B, V]; returns tokens [B] int32."""
    sampled, filtered = flags[0], flags[1]
    lg = process_logits(logits, samp, flags, presence)
    tok_g = jnp.argmax(lg, axis=-1).astype(jnp.int32)
    if not sampled:
        return tok_g
    keys = _fold_keys(samp["base"], samp["pos"], 1)
    lgf = lg / samp["temp"][:, None]
    if filtered:
        lgf = filter_top_k_top_p(lgf, samp["top_k"], samp["top_p"])
    tok_s = categorical_rows(lgf, keys)
    return jnp.where(samp["greedy"], tok_g, tok_s)


def sampled_decode_scan_body(model, cfg, samp, flags):
    """Per-token scan body of the paged decode block with per-row
    sampling: ``decode_scan_body``'s exact greedy semantics (EOS mask,
    pad emits, frozen lens for done rows) plus the sampling chain.
    carry = (tok, lens, kvs, pos, presence, done, budget); ``pos``
    advances with emitted tokens (frozen rows hold, like lens) so
    multi-step blocks consume consecutive PRNG positions; ``presence``
    (None unless the penalty flag is compiled in) absorbs each emitted
    token so the repetition penalty stays exact across the block.

    ``budget`` is the per-row remaining-token count and ``done`` after
    the scan is the IN-TRACE FINISH BITMAP of the dispatch-ahead
    protocol (PR 14): a row flips done when it emits EOS *or* when its
    budget hits zero, and frozen rows hold their budget like they hold
    lens/pos — so the host can dispatch the next iteration feeding
    these carries device-to-device and poll the bitmap one harvest
    late instead of materializing ``tok`` every iteration.  Vacant
    rows enter with ``done=True`` and ``budget=0``; their budget term
    is inert (done already dominates)."""
    penalty = flags[2]

    def body(carry, _):
        tok, lens_c, kvs_c, pos, presence, done, budget = carry
        logits_t, kvs_c = model.decode_step(tok, lens_c, kvs_c)
        step_samp = dict(samp)
        if flags[0]:
            step_samp["pos"] = pos
        nxt = sample_rows(logits_t, step_samp, flags, presence)
        if cfg.eos_token_id is not None:
            nxt = jnp.where(done, cfg.pad_token_id, nxt)
            done_n = done | (nxt == cfg.eos_token_id)
        else:
            done_n = done
        lens_n = jnp.where(done, lens_c, lens_c + 1)
        pos_n = jnp.where(done, pos, pos + 1)
        # the budget half of the finish bitmap: live rows pay one
        # token; a row whose budget just reached zero emitted its last
        # token THIS step and freezes from the next step on — exactly
        # the host-side ``remaining == 0`` retirement, computed where
        # the dispatch-ahead pipeline can see it without a sync
        budget_n = jnp.where(done, budget, budget - 1)
        done_n = done_n | (budget_n <= 0)
        if penalty:
            oh = jax.nn.one_hot(nxt, presence.shape[-1],
                                dtype=jnp.bool_)
            presence = presence | (oh & ~done[:, None])
        return (nxt, lens_n, kvs_c, pos_n, presence, done_n,
                budget_n), nxt

    return body


def _expand_spec_presence(toks, presence):
    """Per-position presence planes of a verify forward: position j's
    context adds draft candidates < j on top of the base plane
    (``toks[:, 0]``, the last emitted token, is already in the base).
    toks [B, C]; presence [B, V] -> [B, C, V]."""
    b, c = toks.shape
    v = presence.shape[-1]
    oh = jax.nn.one_hot(toks[:, 1:], v, dtype=jnp.int32)
    cum = jnp.cumsum(oh, axis=1) > 0
    return presence[:, None, :] | jnp.concatenate(
        [jnp.zeros((b, 1, v), bool), cum], axis=1)


def spec_greedy_rows(logits, toks, samp, flags, presence=None):
    """The greedy half of a verify forward under the processor chain:
    per-position argmax of the PROCESSED logits (presence expanded per
    draft position when the penalty flag is in).  Bit-exact with the
    raw argmax for default rows — the greedy spec acceptance path."""
    if flags[2]:
        presence = _expand_spec_presence(toks, presence)
    lg = process_logits(logits, samp, flags, presence)
    return jnp.argmax(lg, axis=-1).astype(jnp.int32)


def spec_sampling_draws(logits, toks, samp, flags, presence=None):
    """Everything stochastic speculative sampling needs from ONE
    verify forward, drawn in-trace so the draws are position-keyed and
    deterministic.  logits [B, C, V] (position j's target logits after
    consuming drafts < j), toks [B, C] (toks[:, 0] = last emitted
    token, toks[:, 1:] = draft candidates).

    Draft distributions here are ONE-HOT: both drafters are
    deterministic proposal mechanisms, so q_j is the point mass at the
    proposed token and the Leviathan/Chen acceptance rule reduces to
    ``accept draft d_j with prob p_j(d_j)`` (min(1, p/q) at q = 1) with
    residual ``max(p - q, 0) ∝ p masked at d_j`` — still exactly
    distribution-preserving: P(emit x) = p(d)·1[x=d] +
    (1-p(d))·p(x)1[x≠d]/(1-p(d)) = p(x).

    Returns (per row, per position j):
    - ``greedy`` [B, C] i32 — argmax of the PROCESSED logits (the
      greedy acceptance path of greedy rows; bit-exact for default
      rows),
    - ``u`` [B, C] f32 — the accept-test uniform (lane 0 of position
      ``pos + j``),
    - ``accept_p`` [B, C] f32 — p_j(d_j), the acceptance probability
      of draft j (column C-1 has no draft and reads 0),
    - ``resample`` [B, C] i32 — the residual draw at j (consumed only
      when j is the first rejection),
    - ``sample`` [B, C] i32 — a draw from the full p_j (consumed only
      as the bonus token after all drafts accept, or as the plain
      sample of a draftless row).  ``resample`` and ``sample`` share
      lane 1 of position ``pos + j``: at most one of them is consumed
      per position, and acceptance at j consumes only lane 0 —
      unconsumed draws are discarded, preserving independence across
      re-drawn (rolled-back) positions."""
    b, c, v = logits.shape
    if flags[2]:
        presence = _expand_spec_presence(toks, presence)
    lg = process_logits(logits, samp, flags, presence)
    greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)

    pos = samp["pos"][:, None] + jnp.arange(c, dtype=jnp.int32)[None, :]
    base = jnp.broadcast_to(samp["base"][:, None, :], (b, c, 2))
    u_keys = _fold_keys(base.reshape(-1, 2), pos.reshape(-1), 0)
    s_keys = _fold_keys(base.reshape(-1, 2), pos.reshape(-1), 1)
    u = jax.vmap(jax.random.uniform)(u_keys).reshape(b, c)

    lgf = lg / samp["temp"][:, None, None]
    if flags[1]:
        lgf = filter_top_k_top_p(
            lgf,
            jnp.broadcast_to(samp["top_k"][:, None], (b, c)),
            jnp.broadcast_to(samp["top_p"][:, None], (b, c)))
    probs = jax.nn.softmax(lgf, axis=-1)
    # draft at position j is the NEXT input token; the last column has
    # no draft (its draws serve only the bonus sample)
    d = jnp.concatenate(
        [toks[:, 1:], jnp.zeros((b, 1), jnp.int32)], axis=1)
    accept_p = jnp.take_along_axis(probs, d[..., None], axis=-1)[..., 0]
    accept_p = accept_p.at[:, -1].set(0.0)
    # residual: p with the draft token masked out (renormalization is
    # categorical-invariant — logits shift by a row constant)
    lg_res = jnp.where(
        jax.nn.one_hot(d, v, dtype=jnp.bool_), -jnp.inf, lgf)
    keys = s_keys.reshape(b, c, 2)
    resample = categorical_rows(lg_res, keys)
    sample = categorical_rows(lgf, keys)
    return greedy, u, accept_p, resample, sample
